"""Knowledge distillation — reference
``contrib/slim/distillation/distiller.py`` (L2/FSP/SoftLabel distillers)
and ``distillation_strategy.py`` (teacher-graph merge).

``merge`` clones the teacher program's ops/vars into the student program
under a name prefix with every teacher var stop-gradient (the reference
merges IrGraphs the same way); the distillers then build a combined loss
from (student var, teacher var) pairs. Everything compiles into ONE XLA
program, so teacher+student run as a single fused step on the chip.
"""

from .... import framework
from ....executor import global_scope
from ....framework import Operator
from .... import layers

__all__ = ["merge", "L2Distiller", "FSPDistiller", "SoftLabelDistiller"]


def merge(teacher_program, student_program, data_name_map=None,
          scope=None, name_prefix="teacher_"):
    """Clone teacher ops/vars into the student program. ``data_name_map``
    maps teacher feed names -> student feed names so both nets read the
    same inputs. Teacher params keep their (prefixed) scope values;
    everything teacher-side is stop_gradient."""
    scope = scope if scope is not None else global_scope()
    data_name_map = dict(data_name_map or {})
    sblock = student_program.global_block()
    tblock = teacher_program.global_block()

    def rename(n):
        return data_name_map.get(n, name_prefix + n)

    for name, var in tblock.vars.items():
        if name in data_name_map:
            continue
        nv = sblock.create_var(
            name=rename(name), shape=list(var.shape), dtype=var.dtype,
            persistable=var.persistable, stop_gradient=True)
        nv.lod_level = getattr(var, "lod_level", 0)
        if var.persistable:
            tv = scope.find_var(name)
            if tv is not None:
                scope.set_var(rename(name), tv)
    for op in tblock.ops:
        inputs = {slot: [rename(n) for n in names]
                  for slot, names in op.inputs.items()}
        outputs = {slot: [rename(n) for n in names]
                   for slot, names in op.outputs.items()}
        sblock.ops.append(Operator(sblock, op.type, inputs, outputs,
                                   dict(op.attrs)))
    student_program._bump()
    return student_program


class L2Distiller:
    """||student_feature - teacher_feature||² (reference L2Distiller)."""

    def __init__(self, student_var_name, teacher_var_name,
                 distillation_loss_weight=1.0):
        self.student = student_var_name
        self.teacher = teacher_var_name
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        block = program.global_block()
        s = block._find_var_recursive(self.student)
        t = block._find_var_recursive(self.teacher)
        diff = layers.elementwise_sub(s, t)
        return layers.scale(layers.reduce_mean(layers.square(diff)),
                            scale=self.weight)


class SoftLabelDistiller:
    """KL between temperature-softened teacher/student logits (reference
    SoftLabelDistiller)."""

    def __init__(self, student_var_name, teacher_var_name,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student = student_var_name
        self.teacher = teacher_var_name
        self.t_s = student_temperature
        self.t_t = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        block = program.global_block()
        s = block._find_var_recursive(self.student)
        t = block._find_var_recursive(self.teacher)
        s_soft = layers.softmax(layers.scale(s, scale=1.0 / self.t_s))
        t_soft = layers.softmax(layers.scale(t, scale=1.0 / self.t_t))
        t_soft.stop_gradient = True
        ce = layers.cross_entropy(s_soft, t_soft, soft_label=True)
        return layers.scale(layers.reduce_mean(ce), scale=self.weight)


class FSPDistiller:
    """Flow-of-solution-procedure matrices matched in L2 (reference
    FSPDistiller): fsp(a, b) = aᵀb / HW over spatial positions."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = list(student_pairs)
        self.teacher_pairs = list(teacher_pairs)
        self.weight = distillation_loss_weight

    @staticmethod
    def _fsp_matrix(a, b):
        # a [N, C1, H, W], b [N, C2, H, W] -> [N, C1, C2]
        n, c1 = a.shape[0], a.shape[1]
        c2 = b.shape[1]
        hw = int(a.shape[2]) * int(a.shape[3])
        fa = layers.reshape(a, [-1, c1, hw])
        fb = layers.transpose(layers.reshape(b, [-1, c2, hw]), [0, 2, 1])
        return layers.scale(layers.matmul(fa, fb), scale=1.0 / hw)

    def distiller_loss(self, program):
        block = program.global_block()
        losses = []
        for (s0, s1), (t0, t1) in zip(self.student_pairs,
                                      self.teacher_pairs):
            sm = self._fsp_matrix(block._find_var_recursive(s0),
                                  block._find_var_recursive(s1))
            tm = self._fsp_matrix(block._find_var_recursive(t0),
                                  block._find_var_recursive(t1))
            losses.append(layers.reduce_mean(
                layers.square(layers.elementwise_sub(sm, tm))))
        total = losses[0]
        for l in losses[1:]:
            total = layers.elementwise_add(total, l)
        return layers.scale(total, scale=self.weight)
