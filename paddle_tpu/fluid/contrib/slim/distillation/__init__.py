from .distiller import (FSPDistiller, L2Distiller, SoftLabelDistiller,
                        merge)

__all__ = ["merge", "L2Distiller", "FSPDistiller", "SoftLabelDistiller"]
