"""Op-frequency statistics over a Program.

Parity: reference ``contrib/op_frequence.py:23`` ``op_freq_statistic`` —
single-op counts plus adjacent (producer -> consumer) pair counts over
the global block, ordered most-frequent first. The pair statistic is
what the reference's fusion-pass authors mined for candidates; here it
doubles as a fusion sanity view on what XLA will see.
"""

from collections import OrderedDict

from ..framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_2_op_freq): OrderedDicts of op-type and
    "producer,consumer" pair counts, sorted descending."""
    if not isinstance(program, Program):
        raise TypeError("The input type should be Porgram."
                        "But you passed in %s" % (type(program)))

    uni = OrderedDict()
    adj = OrderedDict()
    producer = {}  # var name -> op type of its most recent writer

    for op in program.global_block().ops:
        uni[op.type] = uni.get(op.type, 0) + 1
        for name in op.input_arg_names():
            src = producer.get(name)
            if src is not None:
                key = "%s,%s" % (src, op.type)
                adj[key] = adj.get(key, 0) + 1
        for name in op.output_arg_names():
            producer[name] = op.type

    uni = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni, adj
