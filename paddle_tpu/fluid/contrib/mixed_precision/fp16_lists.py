"""AMP op lists: which ops run in low precision (bf16/fp16), which must
stay fp32, and which follow their inputs.

Parity: reference ``contrib/mixed_precision/fp16_lists.py``. TPU note: the
white list is the MXU ops (matmul/conv) — on TPU the low-precision dtype of
choice is bfloat16, whose fp32-range exponent makes loss scaling optional.
"""

__all__ = ["AutoMixedPrecisionLists"]

# ops that benefit from low precision (MXU-bound)
white_list = {
    "conv2d", "conv3d", "depthwise_conv2d", "conv2d_transpose",
    "conv3d_transpose", "matmul", "mul", "bmm",
    # the pallas kernel does its matmuls in the INPUT dtype with f32
    # accumulation (softmax stays f32 internally), so bf16 inputs hit
    # the MXU at full rate
    "fused_multihead_attention",
    "fused_multihead_attention_packed",
}

# numerically sensitive ops kept in fp32
black_list = {
    "exp", "log", "square", "softmax", "log_softmax", "mean", "sum",
    "reduce_sum", "reduce_mean", "cos_sim", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "cross_entropy",
    "group_norm", "instance_norm", "l2_normalize",
}

# everything else follows its inputs (elementwise, activations, shape ops)
gray_list = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min", "relu", "gelu",
    "tanh", "sigmoid", "dropout", "pool2d", "pool3d", "reshape", "transpose",
    "concat", "split", "slice", "flatten", "squeeze", "unsqueeze", "stack",
    "scale", "cast", "pad", "gather", "lookup_table", "lookup_table_v2",
    # TPU deviation from the reference (which blacklists both for
    # fp16): the norms follow their inputs. bf16 shares fp32's exponent
    # and both lowerings compute stats and normalize in f32 regardless
    # of the activation dtype (ops/nn.py), so bf16 norm I/O is safe —
    # and norm I/O dominates HBM traffic (all of ResNet's activations;
    # 24 layer_norms per BERT step). A caller that wants the reference
    # behavior passes custom_black_list=["batch_norm", "layer_norm"].
    "batch_norm", "layer_norm",
}


class AutoMixedPrecisionLists:
    """User-tunable white/black lists (reference ``fp16_lists.py:23``)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set(gray_list)
        self.black_varnames = set(custom_black_varnames or [])
        for t in custom_white_list or []:
            self.black_list.discard(t)
            self.white_list.add(t)
        for t in custom_black_list or []:
            self.white_list.discard(t)
            self.black_list.add(t)
