"""AMP program rewrite: insert cast ops around white/black-listed ops.

Parity: reference ``contrib/mixed_precision/fp16_utils.py``
(``rewrite_program``). Parameters stay fp32 (master weights); casts are
in-graph, so the autodiff replay differentiates through them and gradients
arrive fp32. XLA fuses the casts into the surrounding ops — on TPU a
bf16 cast feeding the MXU is free.
"""

import numpy as np

from ... import framework
from ...framework import convert_dtype

__all__ = ["rewrite_program", "cast_model_to_fp16"]

_FLOAT32 = np.dtype("float32")


def _is_float(dtype):
    d = np.dtype(convert_dtype(dtype))
    return np.issubdtype(d, np.floating) or "float" in d.name  # incl. bfloat16


def _is_fp32(var):
    """True when var's dtype normalizes to float32. convert_dtype (not raw
    np.dtype) so a var already rewritten to "bfloat16" doesn't raise."""
    if var is None or var.dtype is None:
        return False
    try:
        return np.dtype(convert_dtype(var.dtype)) == _FLOAT32
    except TypeError:
        return False


def _insert_cast(block, new_ops, cache, name, dest_dtype, suffix):
    """Emit (or reuse) a cast of var `name` to dest_dtype; returns new name."""
    key = (name, suffix)
    if key in cache:
        return cache[key]
    src = block._find_var_recursive(name)
    cast_name = name + suffix
    # stop_gradient must stay False: the autodiff replay cuts grads at
    # stop_gradient vars, and casts sit on the param->loss path
    block.create_var(name=cast_name, shape=list(src.shape),
                     dtype=dest_dtype, persistable=False,
                     stop_gradient=False)
    op = framework.Operator(block, "cast", {"X": [name]},
                            {"Out": [cast_name]},
                            {"out_dtype": np.dtype(dest_dtype).name
                             if np.dtype(dest_dtype).name != "void"
                             else "bfloat16"})
    new_ops.append(op)
    cache[key] = cast_name
    return cast_name


# gray ops whose STATE inputs must never be pulled down to the low
# dtype: batch_norm's running stats feed momentum updates whose
# (1-momentum)*delta terms fall below the bf16 ulp, and its scale/bias
# are optimizer-owned parameters — only the activation X follows the
# low chain (the lowering computes stats and rsqrt in f32 regardless)
_KEEP_FP32_SLOTS = {
    "batch_norm": ("Scale", "Bias", "Mean", "Variance"),
    "layer_norm": ("Scale", "Bias"),
}

# gray ops where only SOME outputs become low-precision: batch_norm's
# MeanOut/VarianceOut alias the f32 running stats and SavedMean/
# SavedVariance stay in the stats dtype — only Y follows X. Ops absent
# from this map mark all float outputs low (the default gray rule).
_LOW_OUTPUT_SLOTS = {
    "batch_norm": ("Y",),
    "layer_norm": ("Y",),
}


def rewrite_program(main_program, amp_lists, dest_dtype="bfloat16"):
    """Walk the forward block: white ops get low-precision inputs, black ops
    get fp32 inputs. Gray ops are untouched (jnp promotion handles mixed
    inputs)."""
    low = convert_dtype(dest_dtype)
    block = main_program.global_block()
    low_suffix = ".cast_" + dest_dtype
    fp32_suffix = ".cast_fp32"
    cache = {}
    new_ops = []
    low_vars = set()  # var names whose produced value is low precision

    for op in list(block.ops):
        if op.type == "autodiff":
            new_ops.append(op)
            continue
        if op.type in amp_lists.white_list and not (
                set(op.input_arg_names()) & amp_lists.black_varnames):
            for slot, names in op.inputs.items():
                casted = []
                for n in names:
                    v = block._find_var_recursive(n)
                    if n not in low_vars and _is_fp32(v):
                        casted.append(_insert_cast(
                            block, new_ops, cache, n, low, low_suffix))
                    else:
                        casted.append(n)
                op.inputs[slot] = casted
            for out in op.output_arg_names():
                v = block._find_var_recursive(out)
                if _is_fp32(v):
                    v.dtype = convert_dtype(dest_dtype)
                    low_vars.add(out)
        elif op.type in amp_lists.black_list:
            for slot, names in op.inputs.items():
                casted = []
                for n in names:
                    if n in low_vars:
                        casted.append(_insert_cast(
                            block, new_ops, cache, n, _FLOAT32, fp32_suffix))
                    else:
                        casted.append(n)
                op.inputs[slot] = casted
        else:
            # gray: if any input is low, pull the remaining fp32 float
            # inputs down too (else jnp promotion silently re-widens the
            # whole chain, e.g. a conv's fp32 bias) and mark outputs low
            if any(n in low_vars for n in op.input_arg_names()):
                keep = _KEEP_FP32_SLOTS.get(op.type, ())
                for slot, names in op.inputs.items():
                    if slot in keep:
                        continue
                    casted = []
                    for n in names:
                        v = block._find_var_recursive(n)
                        if n not in low_vars and _is_fp32(v):
                            casted.append(_insert_cast(
                                block, new_ops, cache, n, low, low_suffix))
                        else:
                            casted.append(n)
                    op.inputs[slot] = casted
                low_slots = _LOW_OUTPUT_SLOTS.get(op.type)
                for slot, names in op.outputs.items():
                    if low_slots is not None and slot not in low_slots:
                        continue
                    for out in names:
                        v = block._find_var_recursive(out)
                        if v is not None and v.dtype is not None and \
                                _is_float(v.dtype):
                            low_vars.add(out)
        new_ops.append(op)
    block.ops = new_ops
    main_program._bump()
    return main_program


def cast_model_to_fp16(program, amp_lists=None, dest_dtype="bfloat16"):
    """Inference-side whole-model cast (reference ``fp16_utils.py``
    ``cast_model_to_fp16``): same rewrite, no backward expected."""
    from .fp16_lists import AutoMixedPrecisionLists

    return rewrite_program(program, amp_lists or AutoMixedPrecisionLists(),
                           dest_dtype)
