"""mixed_precision.decorate — the AMP optimizer wrapper.

Parity: reference ``contrib/mixed_precision/decorator.py:216`` (`decorate`)
and ``OptimizerWithMixedPrecision:27``. TPU-first defaults: bfloat16 (fp32
exponent range → ``init_loss_scaling=1.0`` and no dynamic scaling needed);
fp16 semantics (scaling + inf/nan-gated updates) are kept for parity and
for the rare fp16 deployment.

Dynamic loss scaling: the loss is multiplied by the ``loss_scaling``
*variable* in-graph (so each step trains with the current scale) and grads
are divided by the same pre-update value. Grads are checked with
``isfinite``; on overflow the whole gradient set is zeroed for that step (a
zero-grad optimizer step — accumulator decay still advances, a deliberate
simplification vs the reference's conditional skip block). After
``decr_every_n_nan_or_inf`` consecutive overflow steps the scale is
multiplied by ``decr_ratio``; after ``incr_every_n_steps`` clean steps it
is multiplied by ``incr_ratio``.
"""

from ... import framework, unique_name
from ...framework import default_startup_program

from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program

__all__ = ["decorate", "OptimizerWithMixedPrecision"]


def _scalar_var(block, name, dtype, value, startup=True):
    v = block.create_var(name=name, shape=[1], dtype=dtype, persistable=True)
    if startup:
        sb = default_startup_program().global_block()
        sb.create_var(name=name, shape=[1], dtype=dtype, persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [name]},
                     attrs={"shape": [1], "dtype": dtype, "value": value})
    return v


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio, dest_dtype):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._dest_dtype = dest_dtype
        self._loss_scaling = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        main = loss.block.program
        rewrite_program(main, self._amp_lists, self._dest_dtype)
        params_grads = self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set, callbacks)
        block = main.global_block()

        helper_name = unique_name.generate("loss_scaling")
        if self._use_dynamic:
            self._loss_scaling = _scalar_var(
                block, helper_name, "float32", self._init_loss_scaling)
            self._good_steps = _scalar_var(
                block, helper_name + "_good", "int32", 0)
            self._bad_steps = _scalar_var(
                block, helper_name + "_bad", "int32", 0)

        # Scale the loss. Dynamic mode reads the loss_scaling *variable* at
        # runtime (reference decorator.py:135) so updated scales apply on the
        # next step; static mode bakes the constant into the autodiff op.
        for op in block.ops:
            if op.type == "autodiff":
                if self._use_dynamic:
                    op.attrs["loss_scale_var"] = self._loss_scaling.name
                else:
                    op.attrs["loss_scale"] = self._init_loss_scaling

        new_pg = []
        finite_names = []
        push_ops = [o for o in block.ops if o.type == "distributed_push"]
        if self._use_dynamic:
            for p, g in params_grads:
                fname = g.name + ".finite"
                block.create_var(name=fname, shape=[], dtype="bool",
                                 stop_gradient=True)
                block.append_op("isfinite", {"X": [g.name]},
                                {"Out": [fname]})
                finite_names.append(fname)
            # PS-tier payloads overflow independently of device grads (the
            # embedding cotangent accumulates the most backward factors) —
            # check them too, or an inf push would poison host table rows
            # that have no rollback
            for o in push_ops:
                vname = o.input("Values")[0]
                fname = vname + ".finite"
                block.create_var(name=fname, shape=[], dtype="bool",
                                 stop_gradient=True)
                block.append_op("isfinite", {"X": [vname]}, {"Out": [fname]})
                finite_names.append(fname)
            if not finite_names:
                raise ValueError(
                    "dynamic loss scaling needs at least one gradient to "
                    "check (no device grads and no distributed_push ops)")
            all_finite = finite_names[0]
            for fn in finite_names[1:]:
                nxt = unique_name.generate("all_finite")
                block.create_var(name=nxt, shape=[], dtype="bool",
                                 stop_gradient=True)
                block.append_op("logical_and", {"X": [all_finite], "Y": [fn]},
                                {"Out": [nxt]})
                all_finite = nxt
            gate = unique_name.generate("amp_gate")
            block.create_var(name=gate, shape=[], dtype="float32",
                            stop_gradient=True)
            block.append_op("cast", {"X": [all_finite]}, {"Out": [gate]},
                            {"out_dtype": "float32"})
            self._all_finite = all_finite
            # snapshot the scale the grads were computed with BEFORE the
            # update mutates it — unscaling must divide by the old value
            pre = unique_name.generate("loss_scaling_pre")
            block.create_var(name=pre, shape=[1], dtype="float32",
                             stop_gradient=True)
            block.append_op("assign", {"X": [self._loss_scaling.name]},
                            {"Out": [pre]})
            self._append_scale_update(block, gate)

        inv = 1.0 / self._init_loss_scaling
        for p, g in params_grads:
            if inv != 1.0 or self._use_dynamic:
                # A selected_rows grad must keep (a) its type marker — the
                # optimizer's _sparse_grad check reads var.type — and (b) its
                # name+'@ROWS' binding, else the (n, dim) values array would
                # be applied as a dense [vocab, dim] gradient.
                is_sparse = getattr(g, "type", "lod_tensor") == "selected_rows"

                def _derive(base, suffix):
                    nv = g.block.create_var(
                        name=base + suffix, shape=g.shape, dtype=g.dtype,
                        stop_gradient=True,
                        type="selected_rows" if is_sparse else "lod_tensor")
                    if is_sparse:
                        rows = base + suffix + "@ROWS"
                        g.block.create_var(name=rows, shape=(-1,),
                                           dtype="int32", stop_gradient=True)
                        block.append_op("assign", {"X": [g.name + "@ROWS"]},
                                        {"Out": [rows]})
                    return nv

                scaled = _derive(g.name, ".unscaled")
                if self._use_dynamic:
                    block.append_op("elementwise_div",
                                    {"X": [g.name], "Y": [pre]},
                                    {"Out": [scaled.name]}, {"axis": -1})
                else:
                    block.append_op("scale", {"X": [g.name]},
                                    {"Out": [scaled.name]},
                                    {"scale": inv, "bias": 0.0,
                                     "bias_after_scale": True})
                if self._use_dynamic:
                    # select, not multiply: inf * 0 == nan would poison params
                    zeros = g.block.create_var(
                        name=g.name + ".zeros", shape=g.shape, dtype=g.dtype,
                        stop_gradient=True)
                    block.append_op("zeros_like", {"X": [g.name]},
                                    {"Out": [zeros.name]})
                    gated = _derive(g.name, ".gated")
                    block.append_op("where",
                                    {"Condition": [self._all_finite],
                                     "X": [scaled.name], "Y": [zeros.name]},
                                    {"Out": [gated.name]})
                    scaled = gated
                new_pg.append((p, scaled))
            else:
                new_pg.append((p, g))

        # PS-tier pushes must also be unscaled and overflow-gated: annotate
        # each distributed_push op and move it AFTER the gate computation in
        # program order (its lowering reads the gate/scale bindings).
        if push_ops:
            for o in push_ops:
                block.ops.remove(o)
                if self._use_dynamic:
                    o.attrs["scale_var"] = pre
                    o.attrs["gate_var"] = gate
                else:
                    o.attrs["scale"] = self._init_loss_scaling
                block.ops.append(o)
            loss.block.program._bump()
        return new_pg

    def _append_scale_update(self, block, gate_name):
        """loss_scaling/good_steps/bad_steps update in pure elementwise
        arithmetic (reference ``update_loss_scaling``):

        ready      = good+1 >= incr_every_n_steps
        decr_ready = bad+1  >= decr_every_n_nan_or_inf
        scale' = finite ? (ready ? scale*incr : scale)
                        : (decr_ready ? scale*decr : scale)
        good'  = finite ? (ready ? 0 : good+1) : 0
        bad'   = finite ? 0 : (decr_ready ? 0 : bad+1)
        """
        u = unique_name.generate
        s, good, bad = (self._loss_scaling.name, self._good_steps.name,
                        self._bad_steps.name)

        def tmp(dtype="float32", shape=(1,)):
            n = u("amp_ls")
            block.create_var(name=n, shape=list(shape), dtype=dtype,
                             stop_gradient=True)
            return n

        def plus1_float(counter):
            cf = tmp()
            block.append_op("cast", {"X": [counter]}, {"Out": [cf]},
                            {"out_dtype": "float32"})
            c1 = tmp()
            block.append_op("scale", {"X": [cf]}, {"Out": [c1]},
                            {"scale": 1.0, "bias": 1.0,
                             "bias_after_scale": True})
            return c1

        def ge_const(x, value):
            thresh = tmp()
            block.append_op("fill_constant", outputs={"Out": [thresh]},
                            attrs={"shape": [1], "dtype": "float32",
                                   "value": float(value)})
            gb = tmp("bool")
            block.append_op("greater_equal", {"X": [x], "Y": [thresh]},
                            {"Out": [gb]})
            gf = tmp()
            block.append_op("cast", {"X": [gb]}, {"Out": [gf]},
                            {"out_dtype": "float32"})
            return gf

        good1 = plus1_float(good)
        bad1 = plus1_float(bad)
        ready = ge_const(good1, self._incr_every_n_steps)
        decr_ready = ge_const(bad1, self._decr_every_n_nan_or_inf)

        # factor = finite*(1 + ready*(incr-1)) + (1-finite)*(1 + decr_ready*(decr-1))
        t1 = tmp()
        block.append_op("scale", {"X": [ready]}, {"Out": [t1]},
                        {"scale": self._incr_ratio - 1.0, "bias": 1.0,
                         "bias_after_scale": True})
        t2 = tmp()
        block.append_op("elementwise_mul", {"X": [t1], "Y": [gate_name]},
                        {"Out": [t2]}, {"axis": -1})
        notf = tmp()
        block.append_op("scale", {"X": [gate_name]}, {"Out": [notf]},
                        {"scale": -1.0, "bias": 1.0, "bias_after_scale": True})
        dfac = tmp()
        block.append_op("scale", {"X": [decr_ready]}, {"Out": [dfac]},
                        {"scale": self._decr_ratio - 1.0, "bias": 1.0,
                         "bias_after_scale": True})
        t3 = tmp()
        block.append_op("elementwise_mul", {"X": [notf], "Y": [dfac]},
                        {"Out": [t3]}, {"axis": -1})
        factor = tmp()
        block.append_op("elementwise_add", {"X": [t2], "Y": [t3]},
                        {"Out": [factor]}, {"axis": -1})
        news = tmp()
        block.append_op("elementwise_mul", {"X": [s], "Y": [factor]},
                        {"Out": [news]}, {"axis": -1})
        block.append_op("assign", {"X": [news]}, {"Out": [s]})

        def update_counter(counter, keep_gate, ready_f, c1):
            # counter' = keep_gate * (1-ready_f) * (counter+1)
            t4 = tmp()
            block.append_op("scale", {"X": [ready_f]}, {"Out": [t4]},
                            {"scale": -1.0, "bias": 1.0,
                             "bias_after_scale": True})
            t5 = tmp()
            block.append_op("elementwise_mul", {"X": [t4], "Y": [keep_gate]},
                            {"Out": [t5]}, {"axis": -1})
            t6 = tmp()
            block.append_op("elementwise_mul", {"X": [t5], "Y": [c1]},
                            {"Out": [t6]}, {"axis": -1})
            newc = tmp("int32")
            block.append_op("cast", {"X": [t6]}, {"Out": [newc]},
                            {"out_dtype": "int32"})
            block.append_op("assign", {"X": [newc]}, {"Out": [counter]})

        update_counter(good, gate_name, ready, good1)
        update_counter(bad, notf, decr_ready, bad1)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.5,
             use_dynamic_loss_scaling=False, dest_dtype="bfloat16"):
    """Wrap an optimizer for mixed-precision training (reference
    ``decorator.py:216``). TPU default: bfloat16, static scale 1.0."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dest_dtype)
