"""High-level Trainer API.

Parity: reference ``contrib/trainer.py`` (``Trainer:169`` — the old
``fluid.Trainer`` moved into contrib): program construction from a
``train_func``, an event-driven epoch/step loop
(Begin/EndEpochEvent, Begin/EndStepEvent), test over the for_test
clone, save_params / save_inference_model, and serial-numbered
checkpoint dirs with auto-resume (``CheckpointConfig:100``). The
reference's NCCL2/PS transpile hooks map to this build's fleet tier
and are not re-exposed here (fleet is the supported multi-process
path).
"""

import os

from .. import io as fluid_io
from ..data_feeder import DataFeeder
from ..executor import Executor, Scope, scope_guard
from ..framework import Program, program_guard
from .. import optimizer as opt_module

__all__ = [
    "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent", "EndStepEvent",
    "CheckpointConfig", "Trainer",
]


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        #: set False in the handler to skip this step's metric fetch
        self.fetch_metrics = True


class EndStepEvent(object):
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig(object):
    """Serial-numbered checkpoints under ``checkpoint_dir`` every
    ``epoch_interval`` epochs / ``step_interval`` steps; the newest
    serial is auto-loaded at Trainer construction."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.getcwd()
        self.max_num_checkpoints = int(max_num_checkpoints)
        if self.max_num_checkpoints < 1:
            raise ValueError(
                "max_num_checkpoints must be >= 1 (every save would "
                "otherwise retire itself), got %r" % (max_num_checkpoints,))
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        self.load_serial = None

    def _serial_dir(self, serial):
        return os.path.join(self.checkpoint_dir, "checkpoint_%d" % serial)

    def _latest_serial(self):
        best = -1
        if os.path.isdir(self.checkpoint_dir):
            for name in os.listdir(self.checkpoint_dir):
                if name.startswith("checkpoint_"):
                    try:
                        best = max(best, int(name.split("_")[-1]))
                    except ValueError:
                        pass
        return best


class Trainer(object):
    """``train_func() -> loss`` (or [loss, metric...]) builds the graph;
    ``optimizer_func() -> Optimizer`` supplies the optimizer. ``train``
    drives reader batches through the program firing the event handler
    around every epoch and step."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.__stop = False
        self.trainer_id = 0
        self.checkpoint_cfg = checkpoint_config
        if self.checkpoint_cfg is not None:
            if not isinstance(self.checkpoint_cfg, CheckpointConfig):
                raise TypeError("checkpoint_config must be a "
                                "CheckpointConfig")
            serial = self.checkpoint_cfg._latest_serial()
            self.checkpoint_cfg.load_serial = serial if serial >= 0 else None
        self._next_serial = 0

        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        with program_guard(self.train_program, self.startup_program):
            outs = train_func()
            self.train_func_outputs = outs if isinstance(outs, list) \
                else [outs]
            self.test_program = self.train_program.clone(for_test=True)
            loss = self.train_func_outputs[0]
            opt = optimizer_func()
            if not isinstance(opt, opt_module.Optimizer):
                raise TypeError(
                    "The optimizer should be an instance of Optimizer")
            opt.minimize(loss)
        self.place = place
        self.exe = Executor(place)

        with self._prog_and_scope_guard():
            self.exe.run(self.startup_program)
            if self.checkpoint_cfg and \
                    self.checkpoint_cfg.load_serial is not None:
                d = self.checkpoint_cfg._serial_dir(
                    self.checkpoint_cfg.load_serial)
                fluid_io.load_persistables(self.exe, d, self.train_program)
                self._next_serial = self.checkpoint_cfg.load_serial + 1
            elif param_path and os.path.isdir(param_path):
                fluid_io.load_persistables(self.exe, param_path,
                                           self.train_program)

    def _prog_and_scope_guard(self):
        return scope_guard(self.scope)

    def stop(self):
        """Stop the loop after the current step completes."""
        self.__stop = True

    def _feeder(self, feed_order, program):
        blk = program.global_block()
        feed_vars = [blk.var(n) for n in feed_order]
        return DataFeeder(feed_list=feed_vars)

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        if reader is None or feed_order is None:
            raise ValueError("train() needs reader and feed_order")
        self.__stop = False  # a stop() only covers the loop it interrupted
        feeder = self._feeder(feed_order, self.train_program)
        fetch = [v.name for v in self.train_func_outputs]
        with self._prog_and_scope_guard():
            for epoch_id in range(num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stop:
                        if self.checkpoint_cfg:
                            self._save_checkpoint()
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    metrics = self.exe.run(
                        self.train_program, feed=feeder.feed(data),
                        fetch_list=fetch if begin.fetch_metrics else [])
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    if self.checkpoint_cfg and \
                            (step_id + 1) % \
                            self.checkpoint_cfg.step_interval == 0:
                        self._save_checkpoint()
                event_handler(EndEpochEvent(epoch_id))
                if self.checkpoint_cfg and \
                        (epoch_id + 1) % \
                        self.checkpoint_cfg.epoch_interval == 0:
                    self._save_checkpoint()

    def test(self, reader, feed_order):
        """Mean of each train_func output over the test reader, on the
        for_test clone."""
        import numpy as np

        feeder = self._feeder(feed_order, self.test_program)
        fetch = [v.name for v in self.train_func_outputs]
        sums, count = None, 0
        with self._prog_and_scope_guard():
            for data in reader():
                vals = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=fetch)
                vals = [np.mean(np.asarray(v)) for v in vals]
                sums = vals if sums is None else [
                    a + b for a, b in zip(sums, vals)]
                count += 1
        return [s / max(count, 1) for s in (sums or [])]

    def save_params(self, param_path):
        with self._prog_and_scope_guard():
            fluid_io.save_persistables(self.exe, param_path,
                                       self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        targets = [self.train_func_outputs[i] for i in target_var_indexes]
        with self._prog_and_scope_guard():
            fluid_io.save_inference_model(param_path, feeded_var_names,
                                          targets, self.exe,
                                          main_program=self.test_program)

    def _save_checkpoint(self):
        cfg = self.checkpoint_cfg
        d = cfg._serial_dir(self._next_serial)
        os.makedirs(d, exist_ok=True)
        fluid_io.save_persistables(self.exe, d, self.train_program)
        self._next_serial += 1
        # retire old serials beyond max_num_checkpoints
        import shutil

        serials = sorted(
            int(n.split("_")[-1])
            for n in os.listdir(cfg.checkpoint_dir)
            if n.startswith("checkpoint_") and
            n.split("_")[-1].isdigit())
        # explicit bound: a plain serials[:-N] slice silently retires the
        # WRONG end (or nothing) for degenerate N values
        for old in serials[:max(0, len(serials) - cfg.max_num_checkpoints)]:
            shutil.rmtree(cfg._serial_dir(old), ignore_errors=True)
