"""contrib: AMP, slim (quant), extensions — reference ``python/paddle/fluid/contrib/``."""

from . import (extend_optimizer, inferencer, layers,  # noqa: F401
               memory_usage_calc, mixed_precision, model_stat, op_frequence,
               quantize, reader, slim, trainer, utils)
from .inferencer import Inferencer  # noqa: F401
from .trainer import (  # noqa: F401
    BeginEpochEvent,
    BeginStepEvent,
    CheckpointConfig,
    EndEpochEvent,
    EndStepEvent,
    Trainer,
)
from .extend_optimizer import extend_with_decoupled_weight_decay  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
