"""contrib: AMP, slim (quant), extensions — reference ``python/paddle/fluid/contrib/``."""

from . import (extend_optimizer, layers, memory_usage_calc,  # noqa: F401
               mixed_precision, model_stat, op_frequence, quantize, reader,
               slim, utils)
from .extend_optimizer import extend_with_decoupled_weight_decay  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
