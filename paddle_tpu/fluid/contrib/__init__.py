"""contrib: AMP, slim (quant), extensions — reference ``python/paddle/fluid/contrib/``."""

from . import mixed_precision, slim  # noqa: F401
