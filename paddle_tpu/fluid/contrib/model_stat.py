"""Model PARAMs / FLOPs summary.

Parity: reference ``contrib/model_stat.py:40`` ``summary`` — walk every
block's ops, count parameters and forward FLOPs for the common layer
ops (conv, fc/mul/matmul, pool, activations, batch/layer norm), print a
table, and return the totals. Shapes with a batch (-1) leading dim
count per-example, like the reference.
"""

from collections import OrderedDict

__all__ = ["summary"]


def _numel(shape, skip_batch=True):
    n = 1
    for i, d in enumerate(shape):
        if d < 0:
            if skip_batch and i == 0:
                continue
            d = 1
        n *= d
    return n


def _summary_model(block_vars, op):
    if op.type in ("conv2d", "depthwise_conv2d"):
        k = block_vars[op.input("Filter")[0]].shape
        in_shape = block_vars[op.input("Input")[0]].shape
        out_shape = block_vars[op.output("Output")[0]].shape
        # filter shape is [c_out, c_in // groups, kh, kw] — the group
        # division is already baked into the stored shape
        c_out, c_in_per_group, k_h, k_w = k
        h_out, w_out = out_shape[-2], out_shape[-1]
        kernel_ops = k_h * k_w * c_in_per_group
        params = c_out * kernel_ops
        flops = 2 * h_out * w_out * c_out * kernel_ops
    elif op.type in ("mul", "matmul"):
        from ..framework import Parameter

        y = block_vars.get(op.input("Y")[0])
        if y is None or not isinstance(y, Parameter):
            return None
        in_shape = block_vars[op.input("X")[0]].shape
        out_shape = block_vars[op.output("Out")[0]].shape
        k_in, k_out = y.shape[-2], y.shape[-1]
        params = k_in * k_out
        flops = 2 * k_in * k_out * max(_numel(in_shape) // max(k_in, 1), 1)
    elif op.type == "pool2d":
        in_shape = block_vars[op.input("X")[0]].shape
        out_shape = block_vars[op.output("Out")[0]].shape
        ks = op.attr("ksize", [1, 1])
        params = 0
        flops = _numel(out_shape) * ks[0] * ks[1]
    elif op.type in ("sigmoid", "tanh", "relu", "leaky_relu", "prelu",
                     "gelu"):
        in_shape = block_vars[op.input("X")[0]].shape
        out_shape = block_vars[op.output("Out")[0]].shape
        params = 1 if op.type == "prelu" else 0
        flops = _numel(in_shape)
    elif op.type in ("batch_norm", "layer_norm"):
        xname = op.input("X")[0]
        in_shape = block_vars[xname].shape
        out_key = "Y" if op.output("Y") else "Out"
        out_shape = block_vars[op.output(out_key)[0]].shape
        c = in_shape[1] if len(in_shape) > 1 else in_shape[-1]
        params = c * 2
        flops = _numel(in_shape) * 2
    else:
        return None
    return in_shape, out_shape, params, flops


def summary(main_prog, print_table=True):
    """Collects per-op PARAMs/FLOPs; prints the table (reference prints
    on the terminal) and returns (rows, total_params, total_flops)."""
    rows = []
    total_params = 0
    total_flops = 0
    for blk in main_prog.blocks:
        for op in blk.ops:
            res = _summary_model(blk.vars, op)
            if res is None:
                continue
            info = OrderedDict()
            info["type"] = op.type
            info["input_shape"] = tuple(res[0][1:])
            info["out_shape"] = tuple(res[1][1:])
            info["PARAMs"] = int(res[2])
            info["FLOPs"] = int(res[3])
            rows.append(info)
            total_params += info["PARAMs"]
            total_flops += info["FLOPs"]
    if print_table:
        fmt = "%-18s %-22s %-22s %14s %16s"
        print(fmt % ("type", "input_shape", "out_shape", "PARAMs", "FLOPs"))
        for r in rows:
            print(fmt % (r["type"], r["input_shape"], r["out_shape"],
                         "{:,}".format(r["PARAMs"]),
                         "{:,}".format(r["FLOPs"])))
        print("Total PARAMs: %s (%.4fM)  Total FLOPs: %s (%.2fG)"
              % ("{:,}".format(total_params), total_params / 1e6,
                 "{:,}".format(total_flops), total_flops / 1e9))
    return rows, total_params, total_flops
