"""Shard a batch reader across trainers.

Parity: reference ``contrib/reader/distributed_reader.py:21``
``distributed_batch_reader`` — each trainer yields every
``PADDLE_TRAINERS_NUM``-th batch starting at its ``PADDLE_TRAINER_ID``,
so multi-process data parallelism consumes disjoint batches from one
source reader without a central dispatcher.
"""

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    if not trainer_id < trainers_num:
        raise AssertionError(
            "PADDLE_TRAINER_ID %d must be < PADDLE_TRAINERS_NUM %d"
            % (trainer_id, trainers_num))

    def decorated():
        for batch_id, data in enumerate(batch_reader()):
            if batch_id % trainers_num == trainer_id:
                yield data

    return decorated
