"""Decoupled weight decay as an optimizer class transform.

Parity: reference
``contrib/extend_optimizer/extend_optimizer_with_weight_decay.py:102``
``extend_with_decoupled_weight_decay`` — returns a subclass of the given
optimizer whose ``minimize`` subtracts ``param * coeff`` directly from
each parameter (decoupled from the gradient path, AdamW-style), before
the base optimizer applies the raw-gradient update. ``coeff`` is a
float; ``apply_decay_param_fun(name) -> bool`` filters which parameters
decay.
"""

from ... import optimizer as _optimizer
from ...framework import in_dygraph_mode

__all__ = ["extend_with_decoupled_weight_decay"]


class DecoupledWeightDecay(object):
    def __init__(self, coeff=0.0, apply_decay_param_fun=None, **kwargs):
        if not isinstance(coeff, float):
            raise TypeError("coeff should be float, got %r" % (coeff,))
        self._coeff = coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._decayed_names = set()
        super(DecoupledWeightDecay, self).__init__(**kwargs)

    def _append_decay_ops(self, params_grads):
        from ... import layers

        for param, grad in params_grads:
            if grad is None or self._coeff == 0.0:
                continue
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(param.name):
                continue
            self._decayed_names.add(param.name)
            scaled = layers.scale(param, scale=self._coeff)
            layers.assign(layers.elementwise_sub(param, scaled), param)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        if in_dygraph_mode():
            # eager path, same order as static: decay the parameter
            # arrays first, then the base optimizer applies the raw
            # grads. Run the pending backward up front (exactly what the
            # base minimize would do) so grads exist for the filter.
            from ...framework import _dygraph_tracer

            tracer = _dygraph_tracer()
            if tracer is not None and tracer._tape:
                loss.backward()
            if self._coeff and parameter_list:
                for p in parameter_list:
                    if p is None or p._grad is None or p.stop_gradient:
                        continue
                    if self._apply_decay_param_fun is not None and \
                            not self._apply_decay_param_fun(p.name):
                        continue
                    self._decayed_names.add(p.name)
                    p._ivar = p._ivar * (1.0 - self._coeff)
            return super(DecoupledWeightDecay, self).minimize(
                loss, startup_program, parameter_list, no_grad_set,
                grad_clip=grad_clip)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        # the decay ops run in program order before the optimizer ops —
        # the reference appends them between backward and apply
        self._append_decay_ops(params_grads)
        optimize_ops = self.apply_optimize(loss, startup_program,
                                           params_grads)
        return optimize_ops, params_grads

    def __str__(self):
        return " ".join(["Weight Decay, params:",
                         ",".join(sorted(self._decayed_names))])


def extend_with_decoupled_weight_decay(base_optimizer):
    """Returns class ``OptimizerWithDecoupledWeightDecay`` deriving from
    ``base_optimizer``; construct it with ``weight_decay=`` (coeff) and
    optionally ``apply_decay_param_fun=`` plus the base optimizer's own
    arguments."""
    if not issubclass(base_optimizer, _optimizer.Optimizer):
        raise TypeError(
            "The input(base_optimizer) should be a derived class of "
            "Optimizer, got %r" % (base_optimizer,))

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay=0.0, apply_decay_param_fun=None,
                     **kwargs):
            super(OptimizerWithDecoupledWeightDecay, self).__init__(
                coeff=weight_decay,
                apply_decay_param_fun=apply_decay_param_fun, **kwargs)

    return OptimizerWithDecoupledWeightDecay
