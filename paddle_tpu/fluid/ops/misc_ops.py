"""Remaining Appendix-A op lowerings: LoD rebinding (lod_reset/append),
unique_with_counts, CVM, PSRoI pooling, chunk_eval (SelectedRows
merge/densify live in tensor_ops.py). Reference:
``operators/lod_reset_op.cc``, ``unique_op``, ``cvm_op.cc``,
``psroi_pool_op.cc``, ``chunk_eval_op.cc``."""

import numpy as np

from ..lod import lod_name
from ..registry import register


@register("lod_reset")
def _lod_reset(ctx, op):
    """Rebind the @LOD lengths of X: from Y's lod, from Y's int values
    (offset form), or from the target_lod attr (offsets)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    out_name = op.output("Out")[0]
    ctx.set_output(op, "Out", x)
    y_names = op.input("Y")
    if y_names:
        ylod = ctx.env.get(lod_name(y_names[0]))
        if ylod is not None:
            ctx.set(lod_name(out_name), ylod)
            return
        y = ctx.get(y_names[0])  # int offsets tensor
        offs = jnp.reshape(y, (-1,)).astype(np.dtype("int32"))
        ctx.set(lod_name(out_name), offs[1:] - offs[:-1])
        return
    target = op.attr("target_lod", [])
    offs = np.asarray(target, np.int32)
    ctx.set(lod_name(out_name), jnp.asarray(offs[1:] - offs[:-1]))


@register("lod_append")
def _lod_append(ctx, op):
    """Append a deeper LoD level. Only the innermost level rides the
    device (bounded-LoD), so appending REPLACES the device lengths with
    the new innermost level."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", x)
    out_name = op.output("Out")[0]
    level = op.attr("level", [])
    offs = np.asarray(level, np.int32)
    ctx.set(lod_name(out_name), jnp.asarray(offs[1:] - offs[:-1]))


@register("unique_with_counts")
def _unique_with_counts(ctx, op):
    """Size-preserving unique + per-unique counts (fixed shapes; tail
    slots repeat the fill value with count 0)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    out, idx, counts = jnp.unique(x, return_inverse=True,
                                  return_counts=True, size=x.shape[0])
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Index", idx.astype(np.dtype("int32")))
    ctx.set_output(op, "Count", counts.astype(np.dtype("int32")))


@register("cvm")
def _cvm(ctx, op):
    """Continuous-value model op (reference cvm_op.cc): the first two
    features are show/click counters; use_cvm keeps them log-transformed
    (log(show+1), log(clk+1)-log(show+1)), else they are stripped."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    use_cvm = bool(op.attr("use_cvm", True))
    if use_cvm:
        show = jnp.log(x[:, 0:1] + 1.0)
        clk = jnp.log(x[:, 1:2] + 1.0) - show
        ctx.set_output(op, "Y", jnp.concatenate([show, clk, x[:, 2:]],
                                                axis=1))
    else:
        ctx.set_output(op, "Y", x[:, 2:])


@register("psroi_pool")
def _psroi_pool(ctx, op):
    """Position-sensitive RoI average pooling (reference
    psroi_pool_op.cc): output channel c at bin (i, j) pools input channel
    (c*ph + i)*pw + j over that bin."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")        # [N, C*ph*pw, H, W]
    rois = ctx.get_input(op, "ROIs").reshape(-1, 4)
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    out_c = int(op.attr("output_channels"))
    scale = float(op.attr("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[0]
    rois_num = ctx.get_input(op, "RoisNum")
    from .detection_ops import _rois_num_to_batch_idx

    batch_idx = _rois_num_to_batch_idx(rois_num, R)

    def one_roi(roi, bidx):
        x0, y0 = roi[0] * scale, roi[1] * scale
        x1, y1 = roi[2] * scale, roi[3] * scale
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        img = x[bidx].reshape(out_c, ph, pw, H, W)
        yy = jnp.arange(H, dtype=x.dtype)[None, :]
        xx = jnp.arange(W, dtype=x.dtype)[None, :]
        iy = jnp.arange(ph, dtype=x.dtype)[:, None]
        ix = jnp.arange(pw, dtype=x.dtype)[:, None]
        ys0 = y0 + iy * rh / ph
        ys1 = y0 + (iy + 1) * rh / ph
        xs0 = x0 + ix * rw / pw
        xs1 = x0 + (ix + 1) * rw / pw
        ymask = ((yy >= jnp.floor(ys0)) &
                 (yy < jnp.maximum(jnp.ceil(ys1), jnp.floor(ys0) + 1)))
        xmask = ((xx >= jnp.floor(xs0)) &
                 (xx < jnp.maximum(jnp.ceil(xs1), jnp.floor(xs0) + 1)))
        # mask [1, ph, pw, H, W]: bin (i, j) covers pixel (h, w)
        m = ymask[None, :, None, :, None] & xmask[None, None, :, None, :]
        sel = jnp.where(m, img, 0.0)       # img [C_out, ph, pw, H, W]
        cnt = jnp.maximum(m.sum(axis=(3, 4)), 1)
        return sel.sum(axis=(3, 4)) / cnt  # [C_out, ph, pw]

    out = jax.vmap(one_roi)(rois, batch_idx)
    ctx.set_output(op, "Out", out)


@register("chunk_eval")
def _chunk_eval(ctx, op):
    """IOB/IOE/IOBES chunk F1 (reference chunk_eval_op.cc). Span matching
    is irregular host work, not MXU work — computed via
    ``jax.pure_callback`` (the reference also runs it on CPU)."""
    import jax
    import jax.numpy as jnp

    inference = ctx.get_input(op, "Inference")
    label = ctx.get_input(op, "Label")
    num_chunk_types = int(op.attr("num_chunk_types"))
    scheme = str(op.attr("chunk_scheme", "IOB"))
    excluded = set(op.attr("excluded_chunk_types", []) or [])
    lengths = ctx.env.get(lod_name(op.input("Inference")[0]))
    seq_len_names = op.input("SeqLength")
    if lengths is None and seq_len_names:
        lengths = ctx.get(seq_len_names[0])

    def _extract(seq, n_types, scheme):
        # tag layout (reference): IOB -> 2 tags/type (B, I), O = last
        chunks = []
        start, ctype = None, None
        for i, t in enumerate(seq):
            t = int(t)
            if scheme == "IOB":
                is_o = t >= 2 * n_types
                b = (not is_o) and t % 2 == 0
                ty = t // 2 if not is_o else None
            elif scheme == "plain":
                is_o = t >= n_types
                b = not is_o
                ty = t if not is_o else None
            else:
                raise NotImplementedError(
                    "chunk_scheme %r not supported (IOB, plain)" % scheme)
            if start is not None and (is_o or b or ty != ctype):
                chunks.append((start, i - 1, ctype))
                start, ctype = None, None
            if not is_o and (b or start is None):
                start, ctype = i, ty
        if start is not None:
            chunks.append((start, len(seq) - 1, ctype))
        return set(chunks)

    def host(inf, lab, lens):
        inf = np.asarray(inf).ravel()
        lab = np.asarray(lab).ravel()
        if lens is None or np.size(lens) == 0:
            bounds = [(0, inf.size)]
        else:
            offs = np.concatenate([[0], np.cumsum(np.asarray(lens))])
            bounds = list(zip(offs[:-1], offs[1:]))
        n_inf = n_lab = n_cor = 0
        for s, e in bounds:
            ci = {c for c in _extract(inf[s:e], num_chunk_types, scheme)
                  if c[2] not in excluded}
            cl = {c for c in _extract(lab[s:e], num_chunk_types, scheme)
                  if c[2] not in excluded}
            n_inf += len(ci)
            n_lab += len(cl)
            n_cor += len(ci & cl)
        p = n_cor / n_inf if n_inf else 0.0
        r = n_cor / n_lab if n_lab else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return (np.float32(p), np.float32(r), np.float32(f1),
                np.int32(n_inf), np.int32(n_lab), np.int32(n_cor))

    # int32 counters: x64 is disabled on the device path
    shapes = (jax.ShapeDtypeStruct((), np.float32),) * 3 + \
        (jax.ShapeDtypeStruct((), np.int32),) * 3
    args = (inference, label, lengths if lengths is not None
            else jnp.zeros((0,), np.int32))
    p, r, f1, ni, nl, nc = jax.pure_callback(host, shapes, *args)
    ctx.set_output(op, "Precision", p)
    ctx.set_output(op, "Recall", r)
    ctx.set_output(op, "F1-Score", f1)
    ctx.set_output(op, "NumInferChunks", ni)
    ctx.set_output(op, "NumLabelChunks", nl)
    ctx.set_output(op, "NumCorrectChunks", nc)


@register("tree_conv")
def _tree_conv(ctx, op):
    """Tree-based convolution (TBCNN; reference ``tree_conv_op.cc`` +
    ``math/tree2col.cc``). TPU-first reformulation: the reference walks
    each root's subtree with a DFS and scatters eta-weighted features into
    a patch matrix; here the same patch is three dense masked matmuls —
    depth masks are adjacency powers (trees make first-reach depth
    unique), and the eta_t/l/r coefficient matrices contract against the
    node features on the MXU. EdgeSet rows are 1-indexed (parent, child);
    a 0 entry marks padding.
    """
    import jax
    import jax.numpy as jnp

    nodes = ctx.get_input(op, "NodesVector")   # [B, N, F]
    edges = ctx.get_input(op, "EdgeSet")       # [B, E, 2]
    filt = ctx.get_input(op, "Filter")         # [F, 3, K, NumF]
    max_depth = int(op.attr("max_depth", 2))
    D = float(max_depth)
    N = nodes.shape[1]

    def one(feat, edge):
        u = edge[:, 0].astype(np.dtype("int32"))   # parents, 1-indexed
        v = edge[:, 1].astype(np.dtype("int32"))   # children
        valid = ((u > 0) & (v > 0)).astype(feat.dtype)
        ui = jnp.clip(u - 1, 0, N - 1)
        vi = jnp.clip(v - 1, 0, N - 1)
        adj = jnp.zeros((N, N), feat.dtype).at[ui, vi].add(valid)
        # sibling order: index = 1 + #earlier edges with the same parent
        same = (u[None, :] == u[:, None]).astype(feat.dtype) * \
            valid[None, :] * valid[:, None]
        E = u.shape[0]
        earlier = jnp.tril(jnp.ones((E, E), feat.dtype), k=-1)
        index_e = 1.0 + jnp.sum(same * earlier, axis=1)
        pclen_e = jnp.sum(same, axis=1)
        index = jnp.zeros((N,), feat.dtype).at[vi].add(index_e * valid)
        pclen = jnp.zeros((N,), feat.dtype).at[vi].add(pclen_e * valid)
        frac = jnp.where(pclen <= 1.0, 0.5,
                         (index - 1.0) / jnp.maximum(pclen - 1.0, 1.0))
        # depth-k reachability (k < max_depth); unique per (u, v) in a tree
        w_t = jnp.zeros((N, N), feat.dtype)
        w_l = jnp.zeros((N, N), feat.dtype)
        w_r = jnp.zeros((N, N), feat.dtype)
        reach = jnp.eye(N, dtype=feat.dtype)
        for k in range(max_depth):
            eta_t = (D - k) / D
            w_t = w_t + reach * eta_t
            w_l = w_l + reach * ((1.0 - eta_t) * frac)[None, :]
            w_r = w_r + reach * ((1.0 - eta_t) * (1.0 - frac))[None, :]
            reach = reach @ adj
        # [N, F] patches per coefficient family -> contract with Filter
        pt, pl, pr = w_t @ feat, w_l @ feat, w_r @ feat
        return (jnp.einsum("nf,fko->nko", pt, filt[:, 0]) +
                jnp.einsum("nf,fko->nko", pl, filt[:, 1]) +
                jnp.einsum("nf,fko->nko", pr, filt[:, 2]))

    out = jax.vmap(one)(nodes, edges)   # [B, N, K, NumF]
    ctx.set_output(op, "Out", out)


@register("py_func")
def _py_func(ctx, op):
    """Host-Python forward via jax.pure_callback; custom backward (when
    the layer registered one) via jax.custom_vjp whose bwd is a second
    host callback fed (x..., out..., dout...) minus the skip slots.
    Reference: operators/py_func_op.cc."""
    import jax
    import jax.numpy as jnp

    from ..layers.nn import _PYFUNC_TABLE

    func, bwd, x_skip, out_skip = _PYFUNC_TABLE[int(op.attr("func_id"))]
    xs = [ctx.get(n) for n in op.input("X")]
    out_specs = tuple(
        jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
        for s, d in zip(op.attr("out_shapes"), op.attr("out_dtypes")))
    x_specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in xs)

    def fwd_host(*arrs):
        rets = func(*[np.asarray(a) for a in arrs])
        rets = rets if isinstance(rets, (list, tuple)) else [rets]
        if len(rets) != len(out_specs):
            raise ValueError(
                "py_func forward returned %d output(s); %d declared"
                % (len(rets), len(out_specs)))
        return tuple(np.asarray(r).astype(spec.dtype).reshape(spec.shape)
                     for r, spec in zip(rets, out_specs))

    if bwd is None:
        outs = jax.pure_callback(fwd_host, out_specs, *xs)
    else:
        @jax.custom_vjp
        def f(*args):
            return jax.pure_callback(fwd_host, out_specs, *args)

        def f_fwd(*args):
            outs = f(*args)
            return outs, (args, outs)

        def f_bwd(res, douts):
            args, outs_v = res
            # integer inputs take float0 cotangents (jax's tangent type
            # for non-float leaves) — only float inputs ride through the
            # host callback
            is_float = [jnp.issubdtype(a.dtype, jnp.floating)
                        for a in args]
            f_specs = tuple(s for s, fl in zip(x_specs, is_float) if fl)

            def bwd_host(*flat):
                n = len(args)
                m = len(outs_v)
                xs_np = [np.asarray(a) for a in flat[:n]]
                outs_np = [np.asarray(a) for a in flat[n:n + m]]
                douts_np = [np.asarray(a) for a in flat[n + m:]]
                call = [a for a, s in zip(xs_np, x_skip) if not s]
                call += [o for o, s in zip(outs_np, out_skip) if not s]
                call += douts_np
                gs = bwd(*call)
                gs = gs if isinstance(gs, (list, tuple)) else [gs]
                gs = list(gs) + [None] * len(args)
                full = []
                for a, g, fl in zip(args, gs, is_float):
                    if not fl:
                        continue
                    if g is None:
                        full.append(np.zeros(a.shape, a.dtype))
                    else:
                        full.append(np.asarray(g).astype(a.dtype)
                                    .reshape(a.shape))
                return tuple(full)

            f_grads = iter(jax.pure_callback(bwd_host, f_specs, *args,
                                             *outs_v, *douts))
            return tuple(
                next(f_grads) if fl
                else np.zeros(a.shape, jax.dtypes.float0)
                for a, fl in zip(args, is_float))

        f.defvjp(f_fwd, f_bwd)
        outs = f(*xs)
    for n, v in zip(op.output("Out"), outs):
        ctx.set(n, v)
