"""Loss ops.

Parity: reference ``operators/cross_entropy_op.cc``,
``softmax_with_cross_entropy_op.cc``, ``squared_l2_distance``/
``square_error_cost``, ``sigmoid_cross_entropy_with_logits_op.cc``,
``huber_loss_op.cc``, ``log_loss_op.cc``, ``smooth_l1_loss_op.cc``,
``kldiv_loss_op.cc``, ``bpr_loss_op.cc``, ``rank_loss_op.cc``,
``margin_rank_loss_op.cc``, ``hinge_loss_op.cc``, ``center_loss_op``.
"""

import numpy as np

from ..registry import register


def _gather_label_prob(x, label):
    import jax.numpy as jnp

    if label.ndim == x.ndim and label.shape[-1] == 1:
        label = label[..., 0]
    lab = label.astype(np.dtype("int32"))
    return jnp.take_along_axis(x, lab[..., None], axis=-1), lab


@register("cross_entropy")
def _cross_entropy(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # probabilities
    label = ctx.get_input(op, "Label")
    soft = op.attr("soft_label", False)
    ignore = op.attr("ignore_index", -100)
    if soft:
        out = -jnp.sum(label * jnp.log(jnp.clip(x, 1e-20, None)), axis=-1, keepdims=True)
    else:
        p, lab = _gather_label_prob(x, label)
        out = -jnp.log(jnp.clip(p, 1e-20, None))
        out = jnp.where((lab == ignore)[..., None], 0.0, out)
    ctx.set_output(op, "Y", out)


@register("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ctx, op):
    import jax
    import jax.numpy as jnp

    logits = ctx.get_input(op, "Logits")
    label = ctx.get_input(op, "Label")
    soft = op.attr("soft_label", False)
    ignore = op.attr("ignore_index", -100)
    axis = op.attr("axis", -1)
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if soft:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        if label.ndim == logits.ndim and label.shape[axis] == 1:
            lab = jnp.squeeze(label, axis=axis)
        else:
            lab = label
        lab = lab.astype(np.dtype("int32"))
        picked = jnp.take_along_axis(logp, lab[..., None], axis=axis)
        loss = -picked
        loss = jnp.where((lab == ignore)[..., None], 0.0, loss)
    ctx.set_output(op, "Softmax", softmax)
    ctx.set_output(op, "Loss", loss)


@register("square_error_cost")
def _square_error_cost(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", jnp.square(x - y))


@register("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    ignore = op.attr("ignore_index", -100)
    loss = jnp.maximum(x, 0.0) - x * label + jax.nn.softplus(-jnp.abs(x))
    mask = label != ignore
    loss = jnp.where(mask, loss, 0.0)
    if op.attr("normalize", False):
        loss = loss / jnp.maximum(jnp.sum(mask.astype(x.dtype)), 1.0)
    ctx.set_output(op, "Out", loss)


@register("huber_loss")
def _huber_loss(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    delta = op.attr("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * jnp.square(r), delta * (a - 0.5 * delta))
    ctx.set_output(op, "Out", loss)
    ctx.set_output(op, "Residual", r)


@register("log_loss")
def _log_loss(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Predicted")
    label = ctx.get_input(op, "Labels")
    eps = op.attr("epsilon", 1e-4)
    out = -label * jnp.log(p + eps) - (1.0 - label) * jnp.log(1.0 - p + eps)
    ctx.set_output(op, "Loss", out)


@register("smooth_l1_loss")
def _smooth_l1(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    sigma = op.attr("sigma", 1.0)
    in_w = ctx.get_input(op, "InsideWeight", 1.0)
    out_w = ctx.get_input(op, "OutsideWeight", 1.0)
    s2 = sigma * sigma
    d = (x - y) * in_w
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(d), a - 0.5 / s2)
    loss = loss * out_w
    ctx.set_output(op, "Diff", d)
    ctx.set_output(op, "Out", jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True))


@register("kldiv_loss")
def _kldiv_loss(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # log-probabilities
    target = ctx.get_input(op, "Target")
    loss = target * (jnp.log(jnp.clip(target, 1e-20, None)) - x)
    loss = jnp.where(target > 0, loss, 0.0)
    red = op.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    ctx.set_output(op, "Loss", loss)


@register("bpr_loss")
def _bpr_loss(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # (N, C) scores
    label = ctx.get_input(op, "Label")
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[..., 0]
    lab = label.astype(np.dtype("int32"))
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    diff = -(x - pos)
    loss = jnp.sum(jax.nn.softplus(-diff), axis=1, keepdims=True) - jax.nn.softplus(0.0)
    n_neg = x.shape[1] - 1
    ctx.set_output(op, "Y", loss / n_neg)


@register("rank_loss")
def _rank_loss(ctx, op):
    import jax
    import jax.numpy as jnp

    label = ctx.get_input(op, "Label")
    left = ctx.get_input(op, "Left")
    right = ctx.get_input(op, "Right")
    d = left - right
    out = jnp.maximum(d, 0.0) - d * label + jax.nn.softplus(-jnp.abs(d))
    ctx.set_output(op, "Out", out)


@register("margin_rank_loss")
def _margin_rank_loss(ctx, op):
    import jax.numpy as jnp

    label = ctx.get_input(op, "Label")
    x1 = ctx.get_input(op, "X1")
    x2 = ctx.get_input(op, "X2")
    margin = op.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Activated", (out > 0).astype(x1.dtype))


@register("hinge_loss")
def _hinge_loss(ctx, op):
    import jax.numpy as jnp

    logits = ctx.get_input(op, "Logits")
    labels = ctx.get_input(op, "Labels")
    ctx.set_output(op, "Loss", jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits))


@register("center_loss")
def _center_loss(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    centers = ctx.get_input(op, "Centers")
    alpha = ctx.get_input(op, "CenterUpdateRate")
    if label.ndim == 2:
        label = label[..., 0]
    lab = label.astype(np.dtype("int32"))
    picked = centers[lab]
    diff = x - picked
    ctx.set_output(op, "Loss", 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True))
    ctx.set_output(op, "SampleCenterDiff", diff)
    if op.attr("need_update", True) and op.output("CentersOut"):
        counts = jnp.zeros((centers.shape[0],), x.dtype).at[lab].add(1.0)
        upd = jnp.zeros_like(centers).at[lab].add(diff)
        new_centers = centers + jnp.reshape(alpha, ()) * upd / (counts[:, None] + 1.0)
        ctx.set(op.output("CentersOut")[0], new_centers)


@register("mse_loss")
def _mse_loss(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", jnp.mean(jnp.square(x - y)))


@register("npair_loss")
def _npair_loss(ctx, op):
    import jax
    import jax.numpy as jnp

    anchor = ctx.get_input(op, "Anchor")
    positive = ctx.get_input(op, "Positive")
    labels = ctx.get_input(op, "Labels")
    l2_reg = op.attr("l2_reg", 0.002)
    batch = anchor.shape[0]
    sim = anchor @ positive.T
    lab = labels.reshape(-1)
    target = (lab[:, None] == lab[None, :]).astype(anchor.dtype)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.sum(target * logp) / batch
    reg = l2_reg * (jnp.sum(jnp.square(anchor)) + jnp.sum(jnp.square(positive))) / batch
    ctx.set_output(op, "Out", ce + reg)


@register("teacher_student_sigmoid_loss")
def _teacher_student_loss(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    label = ctx.get_input(op, "Label")
    # teacher (label<-1 or >1 encodes soft target regions) — simplified dual loss
    sig = jax.nn.sigmoid(x)
    loss = jnp.maximum(x, 0.0) - x * label + jax.nn.softplus(-jnp.abs(x))
    ctx.set_output(op, "Y", loss)
