"""Detection op family — reference ``paddle/fluid/operators/detection/``
(~27 public layer fns, 15.9k LoC of CPU/CUDA kernels).

TPU-native design rules:
* Every output is FIXED-shape. The reference emits LoD tensors whose size
  depends on the data (NMS survivors, generated proposals); here selection
  ops keep a static top-N and pad the tail (label -1 / zero boxes), which
  is what XLA can compile and what batched TPU serving wants anyway.
* Suppression loops (NMS, bipartite match) are ``lax`` loops over static
  bounds — O(N^2) IoU matrices ride the vector units instead of the
  reference's per-box host loops.
* roi_align/roi_pool sample with gather + bilinear arithmetic (no atomic
  scatter like the CUDA backward; autodiff differentiates the gather).
"""

import numpy as np

from ..registry import register


def _iou_matrix(a, b):
    """[N,4] x [M,4] -> [N,M] IoU (boxes xmin,ymin,xmax,ymax)."""
    import jax.numpy as jnp

    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
        jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register("iou_similarity")
def _iou_similarity(ctx, op):
    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", _iou_matrix(x.reshape(-1, 4),
                                          y.reshape(-1, 4)))


@register("prior_box")
def _prior_box(ctx, op):
    """SSD prior boxes (reference prior_box_op.cc): one box per
    (pixel, aspect_ratio/size) on the feature map, normalized."""
    import jax.numpy as jnp

    feat = ctx.get_input(op, "Input")    # [N, C, H, W]
    image = ctx.get_input(op, "Image")   # [N, C, IH, IW]
    min_sizes = [float(s) for s in op.attr("min_sizes")]
    max_sizes = [float(s) for s in op.attr("max_sizes", []) or []]
    ars = [float(a) for a in op.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in op.attr("variances",
                                           [0.1, 0.1, 0.2, 0.2])]
    flip = bool(op.attr("flip", False))
    clip = bool(op.attr("clip", False))
    step_w = float(op.attr("step_w", 0.0))
    step_h = float(op.attr("step_h", 0.0))
    offset = float(op.attr("offset", 0.5))
    min_max_ar_order = bool(op.attr("min_max_aspect_ratios_order", False))

    H, W = int(feat.shape[2]), int(feat.shape[3])
    IH, IW = int(image.shape[2]), int(image.shape[3])
    sw = step_w or IW / W
    sh = step_h or IH / H

    full_ars = [1.0]
    for ar in ars:
        if abs(ar - 1.0) < 1e-6:
            continue
        full_ars.append(ar)
        if flip:
            full_ars.append(1.0 / ar)

    whs = []  # per-prior (w, h) in pixels
    for si, ms in enumerate(min_sizes):
        if min_max_ar_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[si]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in full_ars[1:]:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in full_ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[si]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    n_priors = len(whs)
    wh = jnp.asarray(whs, np.dtype("float32"))  # [P, 2]

    cx = (jnp.arange(W, dtype=np.dtype("float32")) + offset) * sw
    cy = (jnp.arange(H, dtype=np.dtype("float32")) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)            # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    w2 = wh[None, None, :, 0] / 2.0
    h2 = wh[None, None, :, 1] / 2.0
    boxes = jnp.stack([(cxg - w2) / IW, (cyg - h2) / IH,
                       (cxg + w2) / IW, (cyg + h2) / IH],
                      axis=-1)                  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, np.dtype("float32")),
                           (H, W, n_priors, 4))
    ctx.set_output(op, "Boxes", boxes)
    ctx.set_output(op, "Variances", var)


@register("density_prior_box")
def _density_prior_box(ctx, op):
    """Density prior boxes (reference density_prior_box_op.cc): each
    fixed_size gets density^2 shifted boxes per cell."""
    import jax.numpy as jnp

    feat = ctx.get_input(op, "Input")
    image = ctx.get_input(op, "Image")
    fixed_sizes = [float(s) for s in op.attr("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in op.attr("fixed_ratios", [1.0])]
    densities = [int(d) for d in op.attr("densities", [])]
    variances = [float(v) for v in op.attr("variances",
                                           [0.1, 0.1, 0.2, 0.2])]
    clip = bool(op.attr("clip", False))
    step_w = float(op.attr("step_w", 0.0))
    step_h = float(op.attr("step_h", 0.0))
    offset = float(op.attr("offset", 0.5))
    H, W = int(feat.shape[2]), int(feat.shape[3])
    IH, IW = int(image.shape[2]), int(image.shape[3])
    sw = step_w or IW / W
    sh = step_h or IH / H

    shifts = []  # (dx, dy, w, h) per prior, offsets relative to cell center
    for size, density in zip(fixed_sizes, densities):
        step = size / density
        for r in fixed_ratios:
            bw = size * np.sqrt(r)
            bh = size / np.sqrt(r)
            for di in range(density):
                for dj in range(density):
                    dx = -size / 2.0 + step / 2.0 + dj * step
                    dy = -size / 2.0 + step / 2.0 + di * step
                    shifts.append((dx, dy, bw, bh))
    P = len(shifts)
    sh_arr = jnp.asarray(shifts, np.dtype("float32"))
    cx = (jnp.arange(W, dtype=np.dtype("float32")) + offset) * sw
    cy = (jnp.arange(H, dtype=np.dtype("float32")) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    ctrx = cxg[..., None] + sh_arr[None, None, :, 0]
    ctry = cyg[..., None] + sh_arr[None, None, :, 1]
    w2 = sh_arr[None, None, :, 2] / 2.0
    h2 = sh_arr[None, None, :, 3] / 2.0
    boxes = jnp.stack([(ctrx - w2) / IW, (ctry - h2) / IH,
                       (ctrx + w2) / IW, (ctry + h2) / IH], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, np.dtype("float32")),
                           (H, W, P, 4))
    ctx.set_output(op, "Boxes", boxes)
    ctx.set_output(op, "Variances", var)


@register("anchor_generator")
def _anchor_generator(ctx, op):
    """RPN anchors (reference anchor_generator_op.cc): pixel-space anchors
    per feature cell from anchor_sizes x aspect_ratios."""
    import jax.numpy as jnp

    feat = ctx.get_input(op, "Input")
    sizes = [float(s) for s in op.attr("anchor_sizes")]
    ars = [float(a) for a in op.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in op.attr("variances",
                                           [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in op.attr("stride")]
    offset = float(op.attr("offset", 0.5))
    H, W = int(feat.shape[2]), int(feat.shape[3])
    whs = []
    for ar in ars:
        for s in sizes:
            w = s * np.sqrt(ar)
            h = s / np.sqrt(ar)
            whs.append((w, h))
    A = len(whs)
    wh = jnp.asarray(whs, np.dtype("float32"))
    cx = (jnp.arange(W, dtype=np.dtype("float32")) + offset) * stride[0]
    cy = (jnp.arange(H, dtype=np.dtype("float32")) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    w2 = wh[None, None, :, 0] / 2.0
    h2 = wh[None, None, :, 1] / 2.0
    anchors = jnp.stack([cxg[..., None] - w2, cyg[..., None] - h2,
                         cxg[..., None] + w2, cyg[..., None] + h2], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, np.dtype("float32")),
                           (H, W, A, 4))
    ctx.set_output(op, "Anchors", anchors)
    ctx.set_output(op, "Variances", var)


def _rois_num_to_batch_idx(rois_num, R):
    """RoisNum is the PER-IMAGE RoI count [N]; convert to a per-RoI batch
    index [R] (roi r belongs to the image whose count window covers r)."""
    import jax.numpy as jnp

    if rois_num is None:
        return jnp.zeros((R,), np.dtype("int32"))
    bounds = jnp.cumsum(rois_num.reshape(-1).astype(np.dtype("int32")))
    return (jnp.arange(R)[:, None] >= bounds[None, :]).sum(
        axis=1).astype(np.dtype("int32"))


def _decode_center_size(prior, var, target, norm):
    """box_coder decode_center_size (reference box_coder_op.h)."""
    import jax.numpy as jnp

    pw = prior[..., 2] - prior[..., 0] + (0.0 if norm else 1.0)
    ph = prior[..., 3] - prior[..., 1] + (0.0 if norm else 1.0)
    pcx = prior[..., 0] + pw / 2.0
    pcy = prior[..., 1] + ph / 2.0
    tx, ty, tw, th = (target[..., 0], target[..., 1], target[..., 2],
                      target[..., 3])
    vx, vy, vw, vh = var[..., 0], var[..., 1], var[..., 2], var[..., 3]
    cx = vx * tx * pw + pcx
    cy = vy * ty * ph + pcy
    w = jnp.exp(vw * tw) * pw
    h = jnp.exp(vh * th) * ph
    return jnp.stack([cx - w / 2.0, cy - h / 2.0,
                      cx + w / 2.0 - (0.0 if norm else 1.0),
                      cy + h / 2.0 - (0.0 if norm else 1.0)], axis=-1)


@register("box_coder")
def _box_coder(ctx, op):
    import jax.numpy as jnp

    prior = ctx.get_input(op, "PriorBox").reshape(-1, 4)
    pvar = ctx.get_input(op, "PriorBoxVar")
    target = ctx.get_input(op, "TargetBox")
    code_type = str(op.attr("code_type", "encode_center_size"))
    norm = bool(op.attr("box_normalized", True))
    axis = int(op.attr("axis", 0))
    attr_var = op.attr("variance", [])
    if pvar is not None:
        var_arr = pvar.reshape(-1, 4)
    elif attr_var:
        var_arr = jnp.asarray([float(v) for v in attr_var],
                              np.dtype("float32")).reshape(1, 4)
    else:
        var_arr = jnp.ones((1, 4), np.dtype("float32"))
    if "encode" in code_type:
        # target [M, 4] gt boxes; output [M, N, 4] offsets vs each prior
        t = target.reshape(-1, 4)
        pw = prior[:, 2] - prior[:, 0] + (0.0 if norm else 1.0)
        ph = prior[:, 3] - prior[:, 1] + (0.0 if norm else 1.0)
        pcx = prior[:, 0] + pw / 2.0
        pcy = prior[:, 1] + ph / 2.0
        tw = t[:, 2] - t[:, 0] + (0.0 if norm else 1.0)
        th = t[:, 3] - t[:, 1] + (0.0 if norm else 1.0)
        tcx = t[:, 0] + tw / 2.0
        tcy = t[:, 1] + th / 2.0
        ex = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        ey = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ew = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        eh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([ex, ey, ew, eh], axis=-1)
        out = out / var_arr[None, :, :] if var_arr.shape[0] > 1 else \
            out / var_arr[None, None, 0]
    else:
        # decode: target [N, M, 4]; axis picks which target dim the priors
        # line up with (axis=0 -> dim 1, the SSD layout; axis=1 -> dim 0)
        t = target
        if t.ndim == 2:
            t = t[None]
        if axis == 0:
            p = prior[None, :, :]
            v = (var_arr[None, :, :] if var_arr.shape[0] > 1
                 else var_arr[None, None, 0, :])
        else:
            p = prior[:, None, :]
            v = (var_arr[:, None, :] if var_arr.shape[0] > 1
                 else var_arr[None, None, 0, :])
        out = _decode_center_size(p, v, t, norm)
        if target.ndim == 2:
            out = out[0]
    ctx.set_output(op, "OutputBox", out)


@register("box_clip")
def _box_clip(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")
    im_info = ctx.get_input(op, "ImInfo")  # [N, 3] (h, w, scale)
    h = im_info[..., 0] - 1.0
    w = im_info[..., 1] - 1.0
    # x[..., 0::4] has shape x.shape[:-1] + (k,); the per-image bound must
    # sit on the leading (batch) axis with singletons everywhere else
    shape = (-1,) + (1,) * (x.ndim - 1)
    hx = h.reshape(shape)
    wx = w.reshape(shape)
    out = jnp.stack([
        jnp.clip(x[..., 0::4], 0, wx), jnp.clip(x[..., 1::4], 0, hx),
        jnp.clip(x[..., 2::4], 0, wx), jnp.clip(x[..., 3::4], 0, hx),
    ], axis=-1).reshape(x.shape)
    ctx.set_output(op, "Output", out)


@register("polygon_box_transform")
def _polygon_box_transform(ctx, op):
    """Quad geometry map -> absolute coords (reference
    polygon_box_transform_op.cc): out = 4*pixel_coord - offset."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")  # [N, 8, H, W]
    N, C, H, W = x.shape
    xs = jnp.arange(W, dtype=x.dtype)[None, None, None, :] * 4.0
    ys = jnp.arange(H, dtype=x.dtype)[None, None, :, None] * 4.0
    idx = jnp.arange(C) % 2
    grid = jnp.where(idx[None, :, None, None] == 0, xs, ys)
    ctx.set_output(op, "Output", grid - x)


@register("sigmoid_focal_loss")
def _sigmoid_focal_loss(ctx, op):
    """Reference sigmoid_focal_loss_op.cc: per-class focal BCE; label is
    the 1-based positive class id (0 = background), fg_num normalizes."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")              # [N, C]
    label = ctx.get_input(op, "Label").reshape(-1)  # [N]
    fg = ctx.get_input(op, "FgNum")
    gamma = float(op.attr("gamma", 2.0))
    alpha = float(op.attr("alpha", 0.25))
    C = x.shape[1]
    fg = jnp.maximum(fg.reshape(()).astype(x.dtype), 1.0)
    cls = jnp.arange(1, C + 1, dtype=np.dtype("int32"))[None, :]
    pos = (label[:, None].astype(np.dtype("int32")) == cls).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce_pos = -jnp.log(jnp.maximum(p, 1e-10))
    ce_neg = -jnp.log(jnp.maximum(1 - p, 1e-10))
    loss = pos * alpha * ((1 - p) ** gamma) * ce_pos + \
        (1 - pos) * (1 - alpha) * (p ** gamma) * ce_neg
    ctx.set_output(op, "Out", loss / fg)


@register("yolo_box")
def _yolo_box(ctx, op):
    """Decode YOLOv3 head to boxes+scores (reference yolo_box_op.cc)."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")            # [N, A*(5+C), H, W]
    img_size = ctx.get_input(op, "ImgSize")  # [N, 2] (h, w)
    anchors = [int(a) for a in op.attr("anchors")]
    class_num = int(op.attr("class_num"))
    conf_thresh = float(op.attr("conf_thresh", 0.01))
    downsample = int(op.attr("downsample_ratio", 32))
    clip_bbox = bool(op.attr("clip_bbox", True))
    N, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, x.dtype).reshape(A, 2)
    x5 = x.reshape(N, A, 5 + class_num, H, W)
    tx, ty, tw, th, tconf = (x5[:, :, 0], x5[:, :, 1], x5[:, :, 2],
                             x5[:, :, 3], x5[:, :, 4])
    gx = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    input_w = downsample * W
    input_h = downsample * H
    imh = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    cx = (jax.nn.sigmoid(tx) + gx) / W * imw
    cy = (jax.nn.sigmoid(ty) + gy) / H * imh
    bw = jnp.exp(tw) * an[None, :, 0, None, None] / input_w * imw
    bh = jnp.exp(th) * an[None, :, 1, None, None] / input_h * imh
    x0, y0 = cx - bw / 2.0, cy - bh / 2.0
    x1, y1 = cx + bw / 2.0, cy + bh / 2.0
    if clip_bbox:
        x0 = jnp.clip(x0, 0, imw - 1)
        y0 = jnp.clip(y0, 0, imh - 1)
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(N, -1, 4)
    conf = jax.nn.sigmoid(tconf)
    probs = jax.nn.sigmoid(x5[:, :, 5:]) * conf[:, :, None]
    mask = (conf >= conf_thresh).astype(x.dtype)[:, :, None]
    probs = probs * mask
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    ctx.set_output(op, "Boxes", boxes)
    ctx.set_output(op, "Scores", scores)


@register("multiclass_nms")
@register("multiclass_nms2")
@register("locality_aware_nms")
def _multiclass_nms(ctx, op):
    """Per-class NMS with a FIXED keep_top_k output (reference
    multiclass_nms_op.cc emits an LoD with data-dependent size; here the
    output is [N, keep_top_k, 6] padded with label -1 rows — the
    static-shape TPU serving format). locality_aware_nms shares this
    selection core (its score-fusion step degenerates under static
    shapes)."""
    import jax
    import jax.numpy as jnp

    boxes = ctx.get_input(op, "BBoxes")   # [N, M, 4]
    scores = ctx.get_input(op, "Scores")  # [N, C, M]
    bg = int(op.attr("background_label", 0))
    score_thresh = float(op.attr("score_threshold", 0.0))
    nms_thresh = float(op.attr("nms_threshold", 0.3))
    nms_top_k = int(op.attr("nms_top_k", 64))
    keep_top_k = int(op.attr("keep_top_k", 16))
    eta = float(op.attr("nms_eta", 1.0))
    if keep_top_k <= 0:
        keep_top_k = 16
    N, C, M = scores.shape
    nms_top_k = min(nms_top_k if nms_top_k > 0 else M, M)

    def one_class(b, s):
        # b [M,4], s [M] -> (scores_kept [nms_top_k], idx)
        top_s, top_i = jax.lax.top_k(s, nms_top_k)
        cand = b[top_i]
        iou = _iou_matrix(cand, cand)

        def body(i, keep):
            # suppress j>i overlapping an earlier kept i
            sup = (iou[i] > nms_thresh) & keep[i] & \
                (jnp.arange(nms_top_k) > i)
            return keep & ~sup

        keep0 = top_s > score_thresh
        keep = jax.lax.fori_loop(0, nms_top_k, body, keep0)
        return jnp.where(keep, top_s, -1.0), top_i

    def one_image(b, s):
        # all classes in one vmapped NMS; the background row is forced to
        # score -1 so it can never be selected (cheaper than a C-loop that
        # unrolls the suppression graph per class)
        ks, ki = jax.vmap(one_class, in_axes=(None, 0))(b, s)  # [C, top_k]
        lbl = jnp.broadcast_to(
            jnp.arange(C, dtype=np.dtype("int32"))[:, None], ki.shape)
        if 0 <= bg < C:
            ks = ks.at[bg].set(-1.0)
        all_s = ks.reshape(-1)
        all_i = ki.reshape(-1)
        all_l = lbl.reshape(-1)
        k = min(keep_top_k, all_s.shape[0])
        fs, fi = jax.lax.top_k(all_s, k)
        sel = all_i[fi]
        lab = jnp.where(fs > 0, all_l[fi], -1)
        idx = jnp.where(fs > 0, sel, -1).astype(np.dtype("int32"))
        bsel = b[sel]
        row = jnp.concatenate([
            lab[:, None].astype(b.dtype), fs[:, None], bsel], axis=1)
        # pad to keep_top_k
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, b.dtype)
            row = jnp.concatenate([row, pad], axis=0)
            idx = jnp.concatenate(
                [idx, jnp.full((keep_top_k - k,), -1, np.dtype("int32"))])
        return row, idx

    out, index = jax.vmap(one_image)(boxes, scores)
    ctx.set_output(op, "Out", out)
    if op.output("Index"):
        ctx.set_output(op, "Index", index)
    if op.output("NmsRoisNum"):
        valid = (out[:, :, 0] >= 0).sum(axis=1).astype(np.dtype("int32"))
        ctx.set_output(op, "NmsRoisNum", valid)


@register("bipartite_match")
def _bipartite_match(ctx, op):
    """Greedy bipartite matching (reference bipartite_match_op.cc): each
    column (prior) gets at most one row (gt); max-IoU pairs first."""
    import jax
    import jax.numpy as jnp

    dist = ctx.get_input(op, "DistMat")   # [M_gt, N_prior] (single image)
    match_type = str(op.attr("match_type", "bipartite"))
    overlap_thresh = float(op.attr("dist_threshold", 0.5))
    M, N = dist.shape

    def body(_, carry):
        row_match, col_match, d = carry
        flat = jnp.argmax(d)
        i, j = flat // N, flat % N
        ok = d[i, j] > 0
        row_match = jnp.where(ok, row_match.at[i].set(j), row_match)
        col_match = jnp.where(ok, col_match.at[j].set(i), col_match)
        d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return row_match, col_match, d

    init = (jnp.full((M,), -1, np.dtype("int32")),
            jnp.full((N,), -1, np.dtype("int32")), dist)
    row_match, col_match, _ = jax.lax.fori_loop(0, min(M, N), body, init)
    if match_type == "per_prediction":
        # additionally match any unmatched column whose best gt overlap
        # exceeds the threshold
        best_gt = jnp.argmax(dist, axis=0).astype(np.dtype("int32"))
        best_val = jnp.max(dist, axis=0)
        extra = (col_match < 0) & (best_val > overlap_thresh)
        col_match = jnp.where(extra, best_gt, col_match)
    dmat = jnp.where(col_match >= 0,
                     dist[jnp.clip(col_match, 0, M - 1),
                          jnp.arange(N)], 0.0)
    ctx.set_output(op, "ColToRowMatchIndices", col_match[None, :])
    ctx.set_output(op, "ColToRowMatchDist", dmat[None, :])


@register("target_assign")
def _target_assign(ctx, op):
    """Assign per-prior targets from matched gt (reference
    target_assign_op.cc): out[j] = X[match[j]] where matched, else
    mismatch_value."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")            # [M, K] gt rows (single image)
    match = ctx.get_input(op, "MatchIndices")  # [1, N]
    mismatch = op.attr("mismatch_value", 0)
    m = match.reshape(-1).astype(np.dtype("int32"))
    x2 = x.reshape(x.shape[0], -1) if x.ndim > 1 else x.reshape(-1, 1)
    gathered = x2[jnp.clip(m, 0, x2.shape[0] - 1)]
    matched = (m >= 0)[:, None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, x2.dtype))
    ctx.set_output(op, "Out", out[None])
    ctx.set_output(op, "OutWeight",
                   matched.astype(np.dtype("float32"))[None])


@register("roi_align")
def _roi_align(ctx, op):
    """RoIAlign (reference roi_align_op.cc): bilinear sampling on a
    sampling_ratio x sampling_ratio grid per output bin."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")        # [N, C, H, W]
    rois = ctx.get_input(op, "ROIs")  # [R, 4] (x0,y0,x1,y1) image coords
    roi_batch = ctx.get_input(op, "RoisNum")
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    ratio = int(op.attr("sampling_ratio", -1))
    if ratio <= 0:
        ratio = 2
    N, C, H, W = x.shape
    R = rois.shape[0]
    batch_idx = _rois_num_to_batch_idx(roi_batch, R)

    def one_roi(roi, bidx):
        x0, y0, x1, y1 = roi[0] * scale, roi[1] * scale, roi[2] * scale, \
            roi[3] * scale
        rw = jnp.maximum(x1 - x0, 1.0)
        rh = jnp.maximum(y1 - y0, 1.0)
        bw = rw / pw
        bh = rh / ph
        # sample grid [ph, pw, ratio, ratio]
        iy = jnp.arange(ph, dtype=x.dtype)[:, None, None, None]
        ix = jnp.arange(pw, dtype=x.dtype)[None, :, None, None]
        sy = jnp.arange(ratio, dtype=x.dtype)[None, None, :, None]
        sx = jnp.arange(ratio, dtype=x.dtype)[None, None, None, :]
        yy = y0 + iy * bh + (sy + 0.5) * bh / ratio
        xx = x0 + ix * bw + (sx + 0.5) * bw / ratio
        yy = jnp.clip(yy, 0.0, H - 1.0)
        xx = jnp.clip(xx, 0.0, W - 1.0)
        y0i = jnp.floor(yy).astype(np.dtype("int32"))
        x0i = jnp.floor(xx).astype(np.dtype("int32"))
        y1i = jnp.clip(y0i + 1, 0, H - 1)
        x1i = jnp.clip(x0i + 1, 0, W - 1)
        ly = yy - y0i
        lx = xx - x0i
        img = x[bidx]  # [C, H, W]
        v00 = img[:, y0i, x0i]
        v01 = img[:, y0i, x1i]
        v10 = img[:, y1i, x0i]
        v11 = img[:, y1i, x1i]
        val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
               v10 * ly * (1 - lx) + v11 * ly * lx)
        return val.mean(axis=(-2, -1))  # [C, ph, pw]

    out = jax.vmap(one_roi)(rois.reshape(R, 4), batch_idx)
    ctx.set_output(op, "Out", out)


@register("roi_pool")
def _roi_pool(ctx, op):
    """RoI max pooling (reference roi_pool_op.cc)."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    rois = ctx.get_input(op, "ROIs")
    roi_batch = ctx.get_input(op, "RoisNum")
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[0]
    batch_idx = _rois_num_to_batch_idx(roi_batch, R)

    def one_roi(roi, bidx):
        x0 = jnp.round(roi[0] * scale).astype(np.dtype("int32"))
        y0 = jnp.round(roi[1] * scale).astype(np.dtype("int32"))
        x1 = jnp.round(roi[2] * scale).astype(np.dtype("int32"))
        y1 = jnp.round(roi[3] * scale).astype(np.dtype("int32"))
        rw = jnp.maximum(x1 - x0 + 1, 1)
        rh = jnp.maximum(y1 - y0 + 1, 1)
        img = x[bidx]
        yy = jnp.arange(H)[None, :]
        xx = jnp.arange(W)[None, :]
        iy = jnp.arange(ph)[:, None]
        ix = jnp.arange(pw)[:, None]
        ys0 = y0 + (iy * rh) // ph
        ys1 = y0 + ((iy + 1) * rh + ph - 1) // ph
        xs0 = x0 + (ix * rw) // pw
        xs1 = x0 + ((ix + 1) * rw + pw - 1) // pw
        ymask = (yy >= ys0) & (yy < jnp.maximum(ys1, ys0 + 1))  # [ph, H]
        xmask = (xx >= xs0) & (xx < jnp.maximum(xs1, xs0 + 1))  # [pw, W]
        neg = jnp.asarray(-3.4e38, x.dtype)
        masked = jnp.where(ymask[None, :, :, None, None] &
                           xmask[None, None, None, :, :],
                           img[:, None, :, None, :], neg)
        return masked.max(axis=(2, 4))  # [C, ph, pw]

    out = jax.vmap(one_roi)(rois.reshape(R, 4), batch_idx)
    ctx.set_output(op, "Out", out)


@register("generate_proposals")
def _generate_proposals(ctx, op):
    """RPN proposal generation (reference generate_proposals_op.cc):
    decode deltas at anchors, clip, filter small, NMS — FIXED
    post_nms_topN output (padded with zero boxes)."""
    import jax
    import jax.numpy as jnp

    scores = ctx.get_input(op, "Scores")       # [N, A, H, W]
    deltas = ctx.get_input(op, "BboxDeltas")   # [N, A*4, H, W]
    im_info = ctx.get_input(op, "ImInfo")      # [N, 3]
    anchors = ctx.get_input(op, "Anchors").reshape(-1, 4)
    variances = ctx.get_input(op, "Variances")
    pre_n = int(op.attr("pre_nms_topN", 6000))
    post_n = int(op.attr("post_nms_topN", 1000))
    nms_thresh = float(op.attr("nms_thresh", 0.7))
    min_size = float(op.attr("min_size", 0.1))
    var = (variances.reshape(-1, 4) if variances is not None
           else jnp.ones_like(anchors))
    N = scores.shape[0]
    K = anchors.shape[0]
    sc = scores.transpose(0, 2, 3, 1).reshape(N, -1)
    dl = deltas.transpose(0, 2, 3, 1).reshape(N, -1, 4)
    pre_n = min(pre_n if pre_n > 0 else K, K)
    post_n = min(post_n if post_n > 0 else pre_n, pre_n)

    def one(s, d, info):
        top_s, top_i = jax.lax.top_k(s, pre_n)
        a = anchors[top_i]
        v = var[top_i]
        boxes = _decode_center_size(a, v, d[top_i], norm=False)
        h, w = info[0], info[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, w - 1), jnp.clip(boxes[:, 1], 0, h - 1),
            jnp.clip(boxes[:, 2], 0, w - 1), jnp.clip(boxes[:, 3], 0, h - 1),
        ], axis=1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        # reference scales min_size by the image's resize scale im_info[2]
        ms = min_size * info[2]
        valid = (ws >= ms) & (hs >= ms)
        s2 = jnp.where(valid, top_s, -1e10)
        iou = _iou_matrix(boxes, boxes)

        def body(i, keep):
            sup = (iou[i] > nms_thresh) & keep[i] & (jnp.arange(pre_n) > i)
            return keep & ~sup

        keep = jax.lax.fori_loop(0, pre_n, body, s2 > -1e9)
        s3 = jnp.where(keep, s2, -1e10)
        fs, fi = jax.lax.top_k(s3, post_n)
        return boxes[fi], jnp.maximum(fs, 0.0)

    rois, rscores = jax.vmap(one)(sc, dl, im_info)
    ctx.set_output(op, "RpnRois", rois.reshape(-1, 4))
    ctx.set_output(op, "RpnRoiProbs", rscores.reshape(-1, 1))
    if op.output("RpnRoisNum"):
        ctx.set_output(op, "RpnRoisNum",
                       jnp.full((N,), post_n, np.dtype("int32")))


@register("distribute_fpn_proposals")
def _distribute_fpn_proposals(ctx, op):
    """Route each RoI to its FPN level (reference
    distribute_fpn_proposals_op.cc). Static-shape redesign: every level
    output keeps ALL R slots; off-level rows are zeroed and the restore
    index reassembles the original order."""
    import jax.numpy as jnp

    rois = ctx.get_input(op, "FpnRois").reshape(-1, 4)
    min_level = int(op.attr("min_level", 2))
    max_level = int(op.attr("max_level", 5))
    refer_level = int(op.attr("refer_level", 4))
    refer_scale = float(op.attr("refer_scale", 224))
    R = rois.shape[0]
    ws = rois[:, 2] - rois[:, 0]
    hs = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(ws * hs, 1e-6))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(np.dtype("int32"))
    n_levels = max_level - min_level + 1
    outs = []
    for i in range(n_levels):
        mask = (lvl == (min_level + i)).astype(rois.dtype)[:, None]
        outs.append(rois * mask)
    for i, o in enumerate(outs):
        names = op.output("MultiFpnRois")
        if i < len(names):
            ctx.set(names[i], o)
    ctx.set_output(op, "RestoreIndex",
                   jnp.arange(R, dtype=np.dtype("int32"))[:, None])
    if op.output("MultiLevelRoIsNum"):
        for i, name in enumerate(op.output("MultiLevelRoIsNum")):
            ctx.set(name, (lvl == (min_level + i)).sum().astype(
                np.dtype("int32"))[None])


@register("collect_fpn_proposals")
def _collect_fpn_proposals(ctx, op):
    """Merge per-level RoIs by score, keep post_nms_topN (reference
    collect_fpn_proposals_op.cc)."""
    import jax
    import jax.numpy as jnp

    rois = [ctx.get(n) for n in op.input("MultiLevelRois")]
    scores = [ctx.get(n).reshape(-1) for n in op.input("MultiLevelScores")]
    post_n = int(op.attr("post_nms_topN", 100))
    all_r = jnp.concatenate([r.reshape(-1, 4) for r in rois])
    all_s = jnp.concatenate(scores)
    k = min(post_n, all_s.shape[0])
    top_s, top_i = jax.lax.top_k(all_s, k)
    ctx.set_output(op, "FpnRois", all_r[top_i])
    if op.output("RoisNum"):
        ctx.set_output(op, "RoisNum",
                       jnp.asarray([k], np.dtype("int32")))


@register("box_decoder_and_assign")
def _box_decoder_and_assign(ctx, op):
    """Decode per-class deltas and pick the best class's box (reference
    box_decoder_and_assign_op.cc)."""
    import jax.numpy as jnp

    prior = ctx.get_input(op, "PriorBox").reshape(-1, 4)
    pvar = ctx.get_input(op, "PriorBoxVar")
    target = ctx.get_input(op, "TargetBox")   # [R, C*4]
    score = ctx.get_input(op, "BoxScore")     # [R, C]
    R, C4 = target.shape
    C = C4 // 4
    var = pvar.reshape(-1, 4) if pvar is not None else jnp.ones((1, 4))
    t = target.reshape(R, C, 4)
    decoded = _decode_center_size(
        prior[:, None, :], var[:, None, :] if var.shape[0] > 1
        else var[None, :, :], t, norm=False)  # [R, C, 4]
    best = jnp.argmax(score, axis=1)
    assigned = decoded[jnp.arange(R), best]
    ctx.set_output(op, "DecodeBox", decoded.reshape(R, C4))
    ctx.set_output(op, "OutputAssignBox", assigned)


@register("yolov3_loss")
def _yolov3_loss(ctx, op):
    """YOLOv3 training loss (reference yolov3_loss_op.cc): each gt box is
    assigned to its best-IoU anchor shape at its center cell; coordinate
    (sigmoid/log space), objectness (with ignore_thresh) and class BCE
    terms. gt rows with zero area are padding and contribute nothing."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")              # [N, A*(5+C), H, W]
    gtbox = ctx.get_input(op, "GTBox")      # [N, B, 4] (cx,cy,w,h, 0..1)
    gtlabel = ctx.get_input(op, "GTLabel")  # [N, B]
    anchors = [int(a) for a in op.attr("anchors")]
    mask_ids = [int(m) for m in op.attr("anchor_mask")]
    class_num = int(op.attr("class_num"))
    ignore_thresh = float(op.attr("ignore_thresh", 0.7))
    downsample = int(op.attr("downsample_ratio", 32))
    N, _, H, W = x.shape
    B = gtbox.shape[1]
    A = len(mask_ids)
    input_h, input_w = downsample * H, downsample * W
    all_an = jnp.asarray(anchors, x.dtype).reshape(-1, 2)
    an = all_an[jnp.asarray(mask_ids)]
    x5 = x.reshape(N, A, 5 + class_num, H, W)
    px, py, pw_, ph_, pobj = (x5[:, :, 0], x5[:, :, 1], x5[:, :, 2],
                              x5[:, :, 3], x5[:, :, 4])
    pcls = x5[:, :, 5:]  # [N, A, C, H, W]

    # per-gt best anchor (IoU of wh against ALL anchors, centered)
    gw = gtbox[..., 2] * input_w
    gh = gtbox[..., 3] * input_h
    inter = jnp.minimum(gw[..., None], all_an[None, None, :, 0]) * \
        jnp.minimum(gh[..., None], all_an[None, None, :, 1])
    union = gw[..., None] * gh[..., None] + \
        all_an[None, None, :, 0] * all_an[None, None, :, 1] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N, B]
    gt_valid = (gtbox[..., 2] * gtbox[..., 3] > 0)

    gi = jnp.clip((gtbox[..., 0] * W).astype(np.dtype("int32")), 0, W - 1)
    gj = jnp.clip((gtbox[..., 1] * H).astype(np.dtype("int32")), 0, H - 1)

    bce = lambda logit, t: jnp.maximum(logit, 0) - logit * t + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))

    mask_arr = jnp.asarray(mask_ids)

    def per_image(px_, py_, pw2, ph2, pobj_, pcls_, gt, lab, bst, gi_, gj_,
                  gv):
        # positive terms, vectorized over the B gt slots (one gather per
        # prediction tensor instead of a B-times unrolled graph)
        in_mask = jnp.any(bst[:, None] == mask_arr[None, :], axis=1)
        valid = (gv & in_mask).astype(x.dtype)                   # [B]
        la = jnp.argmax(bst[:, None] == mask_arr[None, :], axis=1)
        tx = gt[:, 0] * W - gi_
        ty = gt[:, 1] * H - gj_
        tw = jnp.log(jnp.maximum(
            gt[:, 2] * input_w / all_an[bst, 0], 1e-9))
        th = jnp.log(jnp.maximum(
            gt[:, 3] * input_h / all_an[bst, 1], 1e-9))
        scale = 2.0 - gt[:, 2] * gt[:, 3]
        vx = px_[la, gj_, gi_]                                   # [B]
        vy = py_[la, gj_, gi_]
        vw = pw2[la, gj_, gi_]
        vh = ph2[la, gj_, gi_]
        l_xy = bce(vx, tx) + bce(vy, ty)
        l_wh = jnp.abs(vw - tw) + jnp.abs(vh - th)
        vc = pcls_[la, :, gj_, gi_]                              # [B, C]
        onehot = (jnp.arange(class_num)[None, :] ==
                  lab[:, None]).astype(x.dtype)
        l_cls = jnp.sum(bce(vc, onehot), axis=1)
        loss = jnp.sum(valid * (scale * (l_xy + l_wh) + l_cls))
        # scatter-max folds duplicate gt cells exactly like repeated set(1)
        obj_pos = jnp.zeros((A, H, W), x.dtype).at[la, gj_, gi_].max(valid)
        obj_target = obj_pos
        # objectness: positives target 1; negatives with best pred-IoU over
        # gt above ignore_thresh are ignored
        boxes_pred = None
        gx = (jax.nn.sigmoid(px_) +
              jnp.arange(W, dtype=x.dtype)[None, None, :]) / W
        gy = (jax.nn.sigmoid(py_) +
              jnp.arange(H, dtype=x.dtype)[None, :, None]) / H
        bw = jnp.exp(pw2) * an[:, 0, None, None] / input_w
        bh = jnp.exp(ph2) * an[:, 1, None, None] / input_h
        pred = jnp.stack([gx - bw / 2, gy - bh / 2,
                          gx + bw / 2, gy + bh / 2], axis=-1)  # [A,H,W,4]
        gt_c = jnp.stack([gt[:, 0] - gt[:, 2] / 2, gt[:, 1] - gt[:, 3] / 2,
                          gt[:, 0] + gt[:, 2] / 2, gt[:, 1] + gt[:, 3] / 2],
                         axis=-1)  # [B, 4]
        iou = _iou_matrix(pred.reshape(-1, 4), gt_c)  # [AHW, B]
        iou = jnp.where(gv[None, :], iou, 0.0)
        best_iou = iou.max(axis=1).reshape(A, H, W)
        ignore = (best_iou > ignore_thresh) & (obj_pos < 0.5)
        l_obj = bce(pobj_, obj_target)
        l_obj = jnp.where(ignore, 0.0, l_obj)
        return loss + jnp.sum(l_obj)

    losses = jax.vmap(per_image)(px, py, pw_, ph_, pobj, pcls, gtbox,
                                 gtlabel.astype(np.dtype("int32")), best,
                                 gi, gj, gt_valid)
    ctx.set_output(op, "Loss", losses)


@register("rpn_target_assign")
@register("retinanet_target_assign")
def _rpn_target_assign(ctx, op):
    """Anchor-gt assignment with subsampling (reference
    rpn_target_assign_op.cc). Static-shape redesign: emits FIXED-size
    per-anchor label/weight arrays — weights play the role of the
    reference's sampled index lists (weight 0 = not sampled)."""
    import jax
    import jax.numpy as jnp

    anchors = ctx.get_input(op, "Anchor").reshape(-1, 4)
    gt = ctx.get_input(op, "GtBoxes").reshape(-1, 4)
    is_retina = op.type == "retinanet_target_assign"
    pos_thresh = float(op.attr("rpn_positive_overlap",
                               0.5 if is_retina else 0.7))
    neg_thresh = float(op.attr("rpn_negative_overlap",
                               0.4 if is_retina else 0.3))
    batch_per_im = int(op.attr("rpn_batch_size_per_im", 256))
    fg_frac = float(op.attr("rpn_fg_fraction", 0.5))
    K = anchors.shape[0]
    iou = _iou_matrix(anchors, gt)  # [K, M]
    gt_valid = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = iou.max(axis=1)
    # anchors that are some gt's argmax are positive too
    gt_best = jnp.where(gt_valid, iou.max(axis=0), -1.0)
    is_gt_best = jnp.any(
        (iou == gt_best[None, :]) & gt_valid[None, :] &
        (gt_best[None, :] > 0), axis=1)
    pos = (best_iou >= pos_thresh) | is_gt_best
    neg = best_iou < neg_thresh
    labels = jnp.where(pos, 1, jnp.where(neg, 0, -1))
    # subsample via weights (deterministic: highest-IoU positives, lowest-
    # IoU negatives first — the reference samples randomly)
    n_fg = int(batch_per_im * fg_frac)
    n_bg = batch_per_im - n_fg
    pos_rank_scores = jnp.where(pos, best_iou, -1.0)
    _, pos_sel = jax.lax.top_k(pos_rank_scores, min(n_fg, K))
    neg_rank_scores = jnp.where(neg, 1.0 - best_iou, -1.0)
    _, neg_sel = jax.lax.top_k(neg_rank_scores, min(n_bg, K))
    # top_k pads its result with filler indices when fewer than n_fg/n_bg
    # candidates exist; only ever RAISE a weight so filler slots can't
    # zero out an anchor selected by the other pass
    w = jnp.zeros((K,), np.dtype("float32"))
    w = w.at[pos_sel].max(pos[pos_sel].astype(np.dtype("float32")))
    w = w.at[neg_sel].max(neg[neg_sel].astype(np.dtype("float32")))
    tgt = gt[jnp.clip(best_gt, 0, gt.shape[0] - 1)]
    ctx.set_output(op, "LocationIndex",
                   jnp.arange(K, dtype=np.dtype("int32")))
    ctx.set_output(op, "ScoreIndex",
                   jnp.arange(K, dtype=np.dtype("int32")))
    ctx.set_output(op, "TargetLabel", labels.astype(np.dtype("int32")))
    ctx.set_output(op, "TargetBBox", tgt)
    ctx.set_output(op, "BBoxInsideWeight",
                   (w * pos.astype(np.dtype("float32")))[:, None] *
                   jnp.ones((1, 4), np.dtype("float32")))
    if op.output("ScoreWeight"):
        ctx.set_output(op, "ScoreWeight", w)
    if op.output("ForegroundNumber"):
        ctx.set_output(op, "ForegroundNumber",
                       jnp.maximum(pos.sum(), 1).astype(
                           np.dtype("int32"))[None])


@register("ssd_loss")
def _ssd_loss(ctx, op):
    """SSD multibox loss (reference ssd_loss_op via Python composition):
    per-prior match to gt (best-IoU + threshold), smooth-L1 localization
    on positives, softmax confidence with mask-based hard negative mining
    (rank < neg_pos_ratio * n_pos) — all static shapes; gt rows with zero
    area are padding."""
    import jax
    import jax.numpy as jnp

    loc = ctx.get_input(op, "Location")      # [N, P, 4]
    conf = ctx.get_input(op, "Confidence")   # [N, P, C]
    gtbox = ctx.get_input(op, "GtBox")       # [N, B, 4]
    gtlabel = ctx.get_input(op, "GtLabel")   # [N, B]
    prior = ctx.get_input(op, "PriorBox").reshape(-1, 4)
    pvar = ctx.get_input(op, "PriorBoxVar")
    overlap_thresh = float(op.attr("overlap_threshold", 0.5))
    neg_ratio = float(op.attr("neg_pos_ratio", 3.0))
    background = int(op.attr("background_label", 0))
    loc_w = float(op.attr("loc_loss_weight", 1.0))
    conf_w = float(op.attr("conf_loss_weight", 1.0))
    var = pvar.reshape(-1, 4) if pvar is not None else \
        jnp.asarray([[0.1, 0.1, 0.2, 0.2]], np.dtype("float32"))
    P = prior.shape[0]
    C = conf.shape[-1]
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    v = var if var.shape[0] > 1 else jnp.broadcast_to(var, (P, 4))

    def one(loc_i, conf_i, gt_i, lab_i):
        valid = (gt_i[:, 2] - gt_i[:, 0]) * (gt_i[:, 3] - gt_i[:, 1]) > 0
        iou = _iou_matrix(gt_i, prior)             # [B, P]
        iou = jnp.where(valid[:, None], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=0)
        best_iou = iou.max(axis=0)
        matched = best_iou > overlap_thresh
        g = gt_i[best_gt]
        glab = lab_i[best_gt]
        # encode matched gt against priors
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-6)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-6)
        gcx = g[:, 0] + gw / 2
        gcy = g[:, 1] + gh / 2
        tx = (gcx - pcx) / pw / v[:, 0]
        ty = (gcy - pcy) / ph / v[:, 1]
        tw = jnp.log(gw / pw) / v[:, 2]
        th = jnp.log(gh / ph) / v[:, 3]
        t = jnp.stack([tx, ty, tw, th], axis=1)
        diff = loc_i - t
        ad = jnp.abs(diff)
        sl1 = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5).sum(axis=1)
        n_pos = jnp.maximum(matched.sum(), 1)
        l_loc = jnp.sum(jnp.where(matched, sl1, 0.0))
        # confidence CE: positives -> gt label, negatives -> background
        tgt = jnp.where(matched, glab.astype(np.dtype("int32")),
                        background)
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        ce = -logp[jnp.arange(P), tgt]
        # hard negative mining: rank negatives by CE, keep top
        # neg_ratio * n_pos
        neg_score = jnp.where(matched, -1e10, ce)
        order = jnp.argsort(-neg_score)
        rank = jnp.zeros((P,), np.dtype("int32")).at[order].set(
            jnp.arange(P, dtype=np.dtype("int32")))
        keep_neg = (~matched) & (rank < (neg_ratio * n_pos).astype(
            np.dtype("int32")))
        l_conf = jnp.sum(jnp.where(matched | keep_neg, ce, 0.0))
        return (loc_w * l_loc + conf_w * l_conf) / n_pos.astype(loc.dtype)

    losses = jax.vmap(one)(loc, conf, gtbox,
                           gtlabel.astype(np.dtype("int32")))
    ctx.set_output(op, "Loss", losses[:, None])
