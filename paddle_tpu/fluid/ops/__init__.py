"""Op lowering library — importing this module registers all op rules.

Role parity: reference ``paddle/fluid/operators/`` (341 registered op types).
Each submodule groups ops like the reference's operator directories.
"""

from . import (  # noqa: F401
    activations,
    autodiff,
    collective,
    control_flow,
    creation,
    detection_ops,
    distributed_ops,
    elementwise,
    embedding_ops,
    loss,
    math,
    metrics,
    misc_ops,
    nn,
    optimizer_ops,
    quant_ops,
    rnn_ops,
    sequence_ops,
    structured_loss_ops,
    tensor_ops,
)
