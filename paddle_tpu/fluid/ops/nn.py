"""NN ops: conv, pooling, normalization, softmax, dropout, interpolation.

Parity: reference ``operators/conv_op.cc``, ``pool_op.cc``,
``batch_norm_op.cc``, ``layer_norm_op.cc``, ``group_norm_op.cc``,
``instance_norm_op.cc``, ``softmax_op.cc``, ``dropout_op.cc``,
``interpolate_op.cc``, ``conv_transpose_op.cc``, ``lrn_op.cc``,
``data_norm_op.cc``, ``spectral_norm_op.cc``, ``grid_sampler``/``affine_*``.

Data layout is NCHW (fluid default); XLA:TPU relayouts internally to feed the
MXU for convs, so no manual layout transform is needed. Convs and matmuls
stay whole — XLA tiles them; elementwise epilogues (bias, act) fuse.
"""

import os

import numpy as np

from ..registry import register


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


@register("conv2d")
@register("depthwise_conv2d")
def _conv2d(ctx, op):
    import jax

    x = ctx.get_input(op, "Input")  # NCHW or NHWC (data_format attr)
    w = ctx.get_input(op, "Filter")  # OIHW either way
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1) or 1
    fmt = op.attr("data_format", "NCHW")
    if op.type == "depthwise_conv2d":
        groups = x.shape[-1] if fmt == "NHWC" else x.shape[1]
    if (os.environ.get("PADDLE_TPU_CONV1X1_GEMM") == "1"
            and tuple(w.shape[2:]) == (1, 1) and strides == (1, 1)
            and pads == (0, 0) and groups == 1):
        # Measured NEGATIVE (r5, v5e, ResNet-50 B=256 AMP): pointwise
        # convs as explicit contractions — so autodiff emits dots, not
        # transposed convs, for dx/dw — run at 1566 img/s vs 2424 for
        # the conv lowering (-35%). XLA's conv path fuses the NCHW
        # layouts/epilogues better than its dot path at these shapes;
        # kept env-gated for re-measurement on future toolchains.
        import jax.numpy as jnp

        eq = ("nchw,oc->nohw" if fmt == "NCHW" else "nhwc,oc->nhwo")
        ctx.set_output(op, "Output", jnp.einsum(eq, x, w[:, :, 0, 0]))
        return
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=((pads[0], pads[0]), (pads[1], pads[1])),
        rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=(fmt, "OIHW", fmt),
    )
    ctx.set_output(op, "Output", out)


@register("conv3d")
def _conv3d(ctx, op):
    import jax

    x = ctx.get_input(op, "Input")  # NCDHW
    w = ctx.get_input(op, "Filter")  # OIDHW
    strides = op.attr("strides", [1, 1, 1])
    pads = op.attr("paddings", [0, 0, 0])
    dil = op.attr("dilations", [1, 1, 1])
    groups = op.attr("groups", 1) or 1
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(strides),
        padding=tuple((p, p) for p in pads),
        rhs_dilation=tuple(dil),
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    ctx.set_output(op, "Output", out)


def _deconv(x, w, strides, pads, dils, groups):
    """Fractionally-strided conv (reference conv_transpose semantics:
    out = (H-1)*s + k_eff - 2p): a conv over the lhs-dilated input with a
    spatially FLIPPED kernel. Fluid deconv filters are [C_in, C_out/g,
    *k]; the equivalent forward conv wants [C_out, C_in/g, *k]."""
    import jax

    nd = len(strides)
    cin = w.shape[0]
    cog = w.shape[1]  # C_out / groups
    # [g, C_in/g, C_out/g, *k] -> [g, C_out/g, C_in/g, *k] -> flat OI*k
    wg = w.reshape((groups, cin // groups, cog) + w.shape[2:])
    wg = wg.swapaxes(1, 2).reshape((groups * cog, cin // groups) +
                                   w.shape[2:])
    flip = (slice(None), slice(None)) + (slice(None, None, -1),) * nd
    wg = wg[flip]
    k_eff = [(w.shape[2 + i] - 1) * dils[i] + 1 for i in range(nd)]
    pad = [(k_eff[i] - 1 - pads[i], k_eff[i] - 1 - pads[i])
           for i in range(nd)]
    spatial = "DHW"[-nd:]
    spec = ("NC" + spatial, "OI" + spatial, "NC" + spatial)
    return jax.lax.conv_general_dilated(
        x, wg, window_strides=(1,) * nd, padding=pad,
        lhs_dilation=tuple(strides), rhs_dilation=tuple(dils),
        dimension_numbers=spec, feature_group_count=groups)


@register("conv2d_transpose")
def _conv2d_transpose(ctx, op):
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Filter")  # IOHW in fluid transpose convs
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1) or 1
    ctx.set_output(op, "Output", _deconv(x, w, strides, pads, dil, groups))


@register("conv3d_transpose")
def _conv3d_transpose(ctx, op):
    x = ctx.get_input(op, "Input")
    w = ctx.get_input(op, "Filter")
    strides = tuple(op.attr("strides", [1, 1, 1]))
    pads = list(op.attr("paddings", [0, 0, 0]))
    dil = tuple(op.attr("dilations", [1, 1, 1]))
    groups = op.attr("groups", 1) or 1
    ctx.set_output(op, "Output", _deconv(x, w, strides, pads, dil, groups))


def _pool(x, pooling_type, ksize, strides, pads, ceil_mode, exclusive,
          global_pool, adaptive, data_format="NCHW"):
    import jax
    import jax.numpy as jnp

    nhwc = data_format == "NHWC"
    h, w = (x.shape[1], x.shape[2]) if nhwc else (x.shape[2], x.shape[3])
    if global_pool:
        ksize = (h, w)
        strides = (1, 1)
        pads = (0, 0)
    if adaptive:
        # adaptive pooling: output ksize[i] bins; use reduce over equal splits
        oh, ow = ksize
        assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible dims"
        kh, kw = h // oh, w // ow
        ksize, strides, pads = (kh, kw), (kh, kw), (0, 0)
    ph, pw = (pads[0], pads[0]), (pads[1], pads[1])
    if ceil_mode:
        # add extra (stride-1) padding on the high side so partial windows count
        ph = (pads[0], pads[0] + strides[0] - 1)
        pw = (pads[1], pads[1] + strides[1] - 1)
    if nhwc:
        window = (1,) + tuple(ksize) + (1,)
        strides_full = (1,) + tuple(strides) + (1,)
        pad_full = ((0, 0), ph, pw, (0, 0))
    else:
        window = (1, 1) + tuple(ksize)
        strides_full = (1, 1) + tuple(strides)
        pad_full = ((0, 0), (0, 0), ph, pw)
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides_full, pad_full)
    # avg
    ones = jnp.ones_like(x)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_full, pad_full)
    if exclusive or ceil_mode:
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides_full, pad_full)
        return summed / counts
    return summed / (ksize[0] * ksize[1])


@register("pool2d")
def _pool2d(ctx, op):
    x = ctx.get_input(op, "X")
    out = _pool(
        x,
        op.attr("pooling_type", "max"),
        _pair(op.attr("ksize", [2, 2])),
        _pair(op.attr("strides", [1, 1])),
        _pair(op.attr("paddings", [0, 0])),
        op.attr("ceil_mode", False),
        op.attr("exclusive", True),
        op.attr("global_pooling", False),
        op.attr("adaptive", False),
        op.attr("data_format", "NCHW"),
    )
    ctx.set_output(op, "Out", out)


@register("pool3d")
def _pool3d(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    ksize = tuple(op.attr("ksize", [2, 2, 2]))
    strides = tuple(op.attr("strides", [1, 1, 1]))
    pads = op.attr("paddings", [0, 0, 0])
    ptype = op.attr("pooling_type", "max")
    if op.attr("global_pooling", False):
        ksize = x.shape[2:]
        strides = (1, 1, 1)
        pads = [0, 0, 0]
    window = (1, 1) + ksize
    strides_full = (1, 1) + strides
    pad_full = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides_full, pad_full)
    else:
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_full, pad_full) / int(
            np.prod(ksize)
        )
    ctx.set_output(op, "Out", out)


@register("softmax")
def _softmax(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    ctx.set_output(op, "Out", jax.nn.softmax(x, axis=axis))


@register("log_softmax")
def _log_softmax(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jax.nn.log_softmax(x, axis=op.attr("axis", -1)))


@register("dropout", has_state=True)
def _dropout(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    p = op.attr("dropout_prob", 0.5)
    is_test = op.attr("is_test", False)
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    # Masks come from 8-bit random words, applied multiplicatively. Against
    # bernoulli (32-bit uniform) + where this is 4x less generator traffic
    # and fuses into one VPU pass — measured on v5e BERT-base AMP:
    # 94.8 -> 87.5 ms/step. Keep-probability resolution is 1/256;
    # INFERENCE scales by the EXACT 1-p (reference-checkpoint parity,
    # ADVICE r3 #3) and the realized-keep (thresh/256) correction folds
    # into the TRAIN-time factor, so E[train out] == E[test out] still
    # holds exactly.
    keep = 1.0 - p
    thresh = min(max(int(round(keep * 256.0)), 0 if keep <= 0.0 else 1), 256)
    if is_test:
        out = x * keep if impl == "downgrade_in_infer" else x
        ctx.set_output(op, "Out", out)
        return
    if thresh <= 0 or thresh >= 256:
        # degenerate keep (rounds to 0 or 1): constant output, but the op
        # still consumes its key so the autodiff replay stream and any
        # key-count-sensitive config comparison stay aligned
        ctx.next_rng()
        one_or_zero = (jnp.ones_like if thresh >= 256 else jnp.zeros_like)
        if thresh >= 256:
            # keep-everything grid cell: the downgrade impl must still
            # carry the exact keep so E[train] == x*keep == E[test]
            full = x * keep if impl == "downgrade_in_infer" else x
        else:
            full = jnp.zeros_like(x)
        ctx.set_output(op, "Out", full)
        ctx.set_output(op, "Mask", one_or_zero(x))
        return
    bits = jax.random.bits(ctx.next_rng(), x.shape, jnp.uint8)
    mask = bits < jnp.uint8(thresh)
    realized = thresh / 256.0
    if impl == "upscale_in_train":
        scale = 1.0 / realized             # E[out] == x; infer passes x
    else:
        scale = keep / realized            # E[out] == x*keep == infer
    out = x * (mask.astype(x.dtype) * scale)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Mask", mask.astype(x.dtype))


@register("batch_norm")
def _batch_norm(ctx, op):
    """Training mode computes batch stats and updates running stats
    (persistable writes, committed by the executor); test mode uses running
    stats. Reference ``operators/batch_norm_op.cc``."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    mean = ctx.get_input(op, "Mean")
    var = ctx.get_input(op, "Variance")
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    is_test = op.attr("is_test", False)
    layout = op.attr("data_layout", "NCHW")
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    if is_test or op.attr("use_global_stats", False):
        use_mean, use_var = mean, var
    else:
        # SINGLE-pass stats (jnp.var re-derives the mean — a second
        # full-activation sweep; BN dominates ResNet's step, measured
        # 1478 -> 1946 img/s from this change): E[x-a] and E[(x-a)^2]
        # reduce over the same input in one fused sweep, f32
        # accumulation, SHIFTED by the running mean as anchor — exact
        # algebraically (var = E[(x-a)^2] - E[x-a]^2), and the
        # cancellation error scales with |batch_mean - running_mean|
        # instead of |mean|, vanishing as training settles.
        # Early-training caveat (anchor = fresh running mean = 0): the
        # f32 relative error of use_var is ~(1 + mc^2/var) * 2^-24, so
        # losing even half the mantissa needs |batch_mean - anchor| >
        # ~64*sigma — orders beyond any real pre-BN activation (std-init
        # convs give |mc| ~ 0.01*sigma). The max(., 0) clamp plus eps in
        # rsqrt bound the fallout if it ever triggers; the off-anchor
        # regime is pinned by test_batch_norm_far_anchor_stats.
        anchor = mean.astype(jnp.float32).reshape(bshape)

        # PADDLE_TPU_BN_REMAT=1 wraps the stats sweep in jax.checkpoint
        # so autodiff recomputes the centered f32 activations instead of
        # storing them. Measured on v5e ResNet-50: remat LOSES with
        # bf16 BN I/O (B=128: 55.6 vs 53.9 ms; B=256: 107.5 vs 105.2)
        # AND with f32 I/O (86.7 vs 67.6 ms) — XLA already folds the
        # convert+subtract into the backward reduce fusions, so the
        # checkpoint only adds a redundant recompute. Default off; knob
        # kept for measurement.
        def _stats(xin):
            xc = xin.astype(jnp.float32) - anchor
            return jnp.mean(xc, axis=axes), jnp.mean(xc * xc, axis=axes)

        if os.environ.get("PADDLE_TPU_BN_REMAT", "0") == "1":
            _stats = jax.checkpoint(_stats)
        mc, m2 = _stats(x)
        use_var = jnp.maximum(m2 - mc * mc, 0.0)
        use_mean = mc + anchor.reshape(-1)
        new_mean = momentum * mean + (1.0 - momentum) * use_mean
        new_var = momentum * var + (1.0 - momentum) * use_var
        # MeanOut/VarianceOut alias Mean/Variance in the reference;
        # running stats keep their declared dtype
        for slot, val, ref in (("MeanOut", new_mean, mean),
                               ("VarianceOut", new_var, var)):
            names = op.output(slot)
            if names:
                ctx.set(names[0], val.astype(ref.dtype))
        ctx.set_output(op, "SavedMean", use_mean.astype(mean.dtype))
        ctx.set_output(op, "SavedVariance",
                       (1.0 / jnp.sqrt(use_var + eps)).astype(mean.dtype))

    inv = 1.0 / jnp.sqrt(use_var.astype(jnp.float32) + eps)
    # normalize in x's dtype (reference keeps Y in the input precision;
    # a low-precision program must not silently promote downstream)
    alpha = (inv * scale.astype(jnp.float32)).astype(x.dtype)
    beta = bias.astype(x.dtype)
    out = ((x - use_mean.astype(x.dtype).reshape(bshape))
           * alpha.reshape(bshape) + beta.reshape(bshape))
    ctx.set_output(op, "Y", out)


@register("layer_norm")
def _layer_norm(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    eps = op.attr("epsilon", 1e-5)
    begin = op.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    # two-pass (x - mean)^2 form: measured FASTER than the single-pass
    # E[x^2] variant on BERT-base (189k vs 177k tok/s — the single-pass
    # rewrite cost more than the fused second reduce) and numerically
    # stabler per-row; batch_norm differs (see there). Under AMP the op
    # is GRAY: x arrives bf16, stats and normalize run in f32 (the
    # converts fuse into the reduces), and Y casts back to x's dtype —
    # per-row bf16 stats over 768 elements would be too coarse.
    xf = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) / jnp.sqrt(var + eps)
    norm_shape = x.shape[begin:]
    if scale is not None:
        out = out * scale.astype(jnp.float32).reshape(norm_shape)
    if bias is not None:
        out = out + bias.astype(jnp.float32).reshape(norm_shape)
    ctx.set_output(op, "Y", out.astype(x.dtype))
    # stats keep their DECLARED dtype (f32 under AMP where X is bf16 but
    # the stat vars stay f32; the input dtype in all-bf16 programs) —
    # same convention as batch_norm's SavedMean/SavedVariance
    for slot, val in (("Mean", mean), ("Variance", var)):
        names = op.output(slot)
        if names:
            ctx.set(names[0], jnp.reshape(val, (-1,)).astype(
                ctx.var_dtype(names[0])))


@register("group_norm")
def _group_norm(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # NCHW
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    eps = op.attr("epsilon", 1e-5)
    groups = op.attr("groups")
    n, c = x.shape[:2]
    gx = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, gx.ndim))
    mean = jnp.mean(gx, axis=axes, keepdims=True)
    var = jnp.var(gx, axis=axes, keepdims=True)
    out = ((gx - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        out = out * scale.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    ctx.set_output(op, "Y", out)
    ctx.set_output(op, "Mean", jnp.reshape(mean, (n, groups)))
    ctx.set_output(op, "Variance", jnp.reshape(var, (n, groups)))


@register("instance_norm")
def _instance_norm(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # NCHW
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    eps = op.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if scale is not None:
        out = out * scale.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    ctx.set_output(op, "Y", out)


@register("data_norm")
def _data_norm(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    size = ctx.get_input(op, "BatchSize")
    total = ctx.get_input(op, "BatchSum")
    sq = ctx.get_input(op, "BatchSquareSum")
    mean = total / size
    scale = jnp.sqrt(size / sq)
    ctx.set_output(op, "Y", (x - mean) * scale)
    ctx.set_output(op, "Means", mean)
    ctx.set_output(op, "Scales", scale)


@register("spectral_norm")
def _spectral_norm(ctx, op):
    import jax.numpy as jnp

    w = ctx.get_input(op, "Weight")
    u = ctx.get_input(op, "U")
    v = ctx.get_input(op, "V")
    dim = op.attr("dim", 0)
    power_iters = op.attr("power_iters", 1)
    eps = op.attr("eps", 1e-12)
    wmat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(max(power_iters, 0)):
        v = wmat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wmat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wmat @ v
    ctx.set_output(op, "Out", w / sigma)


@register("lrn")
def _lrn(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # NCHW
    n_size = op.attr("n", 5)
    k = op.attr("k", 2.0)
    alpha = op.attr("alpha", 1e-4)
    beta = op.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n_size // 2
    summed = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add, (1, n_size, 1, 1), (1, 1, 1, 1),
        ((0, 0), (half, n_size - 1 - half), (0, 0), (0, 0)),
    )
    ctx.set_output(op, "Out", x / jnp.power(k + alpha * summed, beta))


def _resize(x, out_h, out_w, method, align_corners):
    import jax

    n, c, h, w = x.shape
    return jax.image.resize(
        x, (n, c, out_h, out_w), method=method
    )


def _interp_out_hw(ctx, op, x):
    out_h = op.attr("out_h", -1)
    out_w = op.attr("out_w", -1)
    scale = op.attr("scale", 0.0)
    if op.input("OutSize"):
        sz = np.asarray(ctx.get_input(op, "OutSize"))
        out_h, out_w = int(sz[0]), int(sz[1])
    elif scale and scale > 0:
        out_h, out_w = int(x.shape[2] * scale), int(x.shape[3] * scale)
    return out_h, out_w


@register("bilinear_interp")
def _bilinear_interp(ctx, op):
    x = ctx.get_input(op, "X")
    out_h, out_w = _interp_out_hw(ctx, op, x)
    ctx.set_output(op, "Out", _resize(x, out_h, out_w, "bilinear", op.attr("align_corners", True)))


@register("nearest_interp")
def _nearest_interp(ctx, op):
    x = ctx.get_input(op, "X")
    out_h, out_w = _interp_out_hw(ctx, op, x)
    ctx.set_output(op, "Out", _resize(x, out_h, out_w, "nearest", op.attr("align_corners", True)))


@register("trilinear_interp")
def _trilinear_interp(ctx, op):
    import jax

    x = ctx.get_input(op, "X")  # NCDHW
    out_d = op.attr("out_d", -1)
    out_h = op.attr("out_h", -1)
    out_w = op.attr("out_w", -1)
    n, c = x.shape[:2]
    ctx.set_output(op, "Out", jax.image.resize(x, (n, c, out_d, out_h, out_w), "trilinear"))


@register("affine_channel")
def _affine_channel(ctx, op):
    x = ctx.get_input(op, "X")  # NCHW
    scale = ctx.get_input(op, "Scale")
    bias = ctx.get_input(op, "Bias")
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    ctx.set_output(op, "Out", x * scale.reshape(bshape) + bias.reshape(bshape))


@register("temporal_shift")
def _temporal_shift(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    seg_num = op.attr("seg_num")
    ratio = op.attr("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    back = jnp.concatenate([x5[:, 1:, :c1], jnp.zeros_like(x5[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate([jnp.zeros_like(x5[:, :1, c1:c2]), x5[:, :-1, c1:c2]], axis=1)
    keep = x5[:, :, c2:]
    out = jnp.concatenate([back, fwd, keep], axis=2)
    ctx.set_output(op, "Out", out.reshape(nt, c, h, w))


@register("grid_sampler")
def _grid_sampler(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # NCHW
    grid = ctx.get_input(op, "Grid")  # NHW2 in [-1,1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1, wy1 = gx - x0, gy - y0
    wx0, wy0 = 1.0 - wx1, 1.0 - wy1

    def sample(xi, yi):
        xi = jnp.clip(xi, 0, w - 1).astype(np.dtype("int32"))
        yi = jnp.clip(yi, 0, h - 1).astype(np.dtype("int32"))
        batch = jnp.arange(n)[:, None, None]
        return x[batch, :, yi, xi]  # N,H,W,C

    out = (
        sample(x0, y0) * (wx0 * wy0)[..., None]
        + sample(x1, y0) * (wx1 * wy0)[..., None]
        + sample(x0, y1) * (wx0 * wy1)[..., None]
        + sample(x1, y1) * (wx1 * wy1)[..., None]
    )
    ctx.set_output(op, "Output", jnp.moveaxis(out, -1, 1))


@register("affine_grid")
def _affine_grid(ctx, op):
    import jax.numpy as jnp

    theta = ctx.get_input(op, "Theta")  # N,2,3
    shape = op.attr("output_shape")
    if op.input("OutputShape"):
        shape = [int(v) for v in np.asarray(ctx.get_input(op, "OutputShape"))]
    n, c, h, w = shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    out = jnp.einsum("bhk,bok->bho", jnp.tile(base, (theta.shape[0], 1, 1)), theta)
    ctx.set_output(op, "Output", out.reshape(theta.shape[0], h, w, 2))


@register("im2sequence")
def _im2sequence(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    ksizes = op.attr("kernels")
    strides = op.attr("strides", [1, 1])
    pads = op.attr("paddings", [0, 0, 0, 0])
    patches = jax.lax.conv_general_dilated_patches(
        x, tuple(ksizes), tuple(strides), ((pads[0], pads[2]), (pads[1], pads[3]))
    )
    n, ckk, oh, ow = patches.shape
    ctx.set_output(op, "Out", patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, ckk))


@register("row_conv")
def _row_conv(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # (B, T, D) batched path
    w = ctx.get_input(op, "Filter")  # (future_len, D)
    flen = w.shape[0]
    t = x.shape[-2]
    out = jnp.zeros_like(x)
    for k in range(flen):
        shifted = jnp.pad(x, [(0, 0)] * (x.ndim - 2) + [(0, k), (0, 0)])[..., k:k + t, :]
        out = out + shifted * w[k]
    ctx.set_output(op, "Out", out)


@register("multiplex")
def _multiplex(ctx, op):
    import jax.numpy as jnp

    ids = ctx.get_input(op, "Ids")
    xs = jnp.stack(ctx.get_inputs(op, "X"), axis=0)
    idx = ids.reshape(-1).astype(np.dtype("int32"))
    rows = jnp.arange(idx.shape[0])
    ctx.set_output(op, "Out", xs[idx, rows])


@register("fused_multihead_attention", has_state=True)
def _fused_multihead_attention(ctx, op):
    """One-kernel attention (paddle_tpu/kernels/attention.py) — the
    in-framework form of the reference's multihead_matmul fusion
    (``ir/multihead_matmul_fuse_pass.cc``), available in training too."""
    from ...kernels.attention import fused_attention

    q = ctx.get_input(op, "Q")
    k = ctx.get_input(op, "K")
    v = ctx.get_input(op, "V")
    bias = ctx.get_input(op, "Bias")
    p = float(op.attr("dropout_prob", 0.0))
    is_test = bool(op.attr("is_test", False))
    scale = op.attr("scale", None)
    drop = 0.0 if is_test else p
    key = ctx.next_rng() if drop > 0.0 else None
    ctx.set_output(op, "Out", fused_attention(
        q, k, v, bias, scale=scale, dropout_prob=drop, rng_key=key))


@register("fused_multihead_attention_packed", has_state=True)
def _fused_multihead_attention_packed(ctx, op):
    """Packed-layout ([B, S, H*d]) variant: heads strided inside the
    kernel, no [B, H, S, d] transposes in the graph
    (kernels/attention.py packed tier)."""
    from ...kernels.attention import fused_attention_packed

    q = ctx.get_input(op, "Q")
    k = ctx.get_input(op, "K")
    v = ctx.get_input(op, "V")
    bias = ctx.get_input(op, "Bias")
    p = float(op.attr("dropout_prob", 0.0))
    is_test = bool(op.attr("is_test", False))
    scale = op.attr("scale", None)
    n_heads = int(op.attr("n_heads", 1))
    drop = 0.0 if is_test else p
    key = ctx.next_rng() if drop > 0.0 else None
    ctx.set_output(op, "Out", fused_attention_packed(
        q, k, v, bias, n_heads=n_heads, scale=scale, dropout_prob=drop,
        rng_key=key))


@register("sequence_parallel_attention", has_state=True)
def _sequence_parallel_attention(ctx, op):
    """Long-context attention with the sequence dim sharded over the
    strategy mesh's "sp" axis (kernels/attention.py: ring KV rotation or
    Ulysses all-to-all, picked per the ``strategy`` attr / auto rule).
    Packed [B, S, H*d] in and out; with no mesh (or no "sp" axis) the
    same math runs single-shard, so programs are portable."""
    from ...kernels.attention import sequence_parallel_attention

    q = ctx.get_input(op, "Q")
    k = ctx.get_input(op, "K")
    v = ctx.get_input(op, "V")
    bias = ctx.get_input(op, "Bias")
    p = float(op.attr("dropout_prob", 0.0))
    is_test = bool(op.attr("is_test", False))
    drop = 0.0 if is_test else p
    key = ctx.next_rng() if drop > 0.0 else None
    ctx.set_output(op, "Out", sequence_parallel_attention(
        q, k, v, int(op.attr("n_heads", 1)), bias=bias,
        mesh=getattr(ctx, "mesh", None),    # eager ctx carries no mesh
        seq_axis=str(op.attr("seq_axis", "sp")),
        batch_axis=str(op.attr("batch_axis", "dp")),
        causal=bool(op.attr("causal", False)),
        scale=op.attr("scale", None), dropout_prob=drop, rng_key=key,
        strategy=str(op.attr("strategy", "auto"))))


@register("kv_cache_update")
def _kv_cache_update(ctx, op):
    """Ring-buffer KV cache write (kernels/attention.py): New [B, H, T, d]
    lands at slot CacheLen % C of Cache [B, H, C, d]; OutLen = CacheLen
    + T so decode programs carry the token count on-device (no host
    round-trip between steps)."""
    from ...kernels.attention import kv_cache_update

    cache = ctx.get_input(op, "Cache")
    new = ctx.get_input(op, "New")
    cache_len = ctx.get_input(op, "CacheLen")
    out, out_len = kv_cache_update(cache, new, cache_len)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "OutLen", out_len)


@register("fused_multihead_attention_cache")
def _fused_multihead_attention_cache(ctx, op):
    """Decode-step attention against a KV ring buffer
    (kernels/attention.py attention_with_cache): masked-length fallback
    or the Pallas decode tier at large capacities. Inference-only.
    ``causal_window`` (default off — old programs deserialize unchanged)
    is the speculative-verify form: Q rows are the last Q tokens
    written, each masking the columns written after it."""
    from ...kernels.attention import attention_with_cache

    q = ctx.get_input(op, "Q")
    k_cache = ctx.get_input(op, "KCache")
    v_cache = ctx.get_input(op, "VCache")
    cache_len = ctx.get_input(op, "CacheLen")
    scale = op.attr("scale", None)
    ctx.set_output(op, "Out", attention_with_cache(
        q, k_cache, v_cache, cache_len, scale=scale,
        causal_window=bool(op.attr("causal_window", False))))


@register("paged_kv_cache_update")
def _paged_kv_cache_update(ctx, op):
    """Block-granular KV cache write (kernels/attention.py): New
    [B, H, T, d] scatters through PageTable [B, npages] into the shared
    Pool [P, H, ptok, d] at the slot's logical ring positions; OutLen =
    CacheLen + T. The paged generalization of ``kv_cache_update`` —
    writes may cross page and ring boundaries."""
    from ...kernels.attention import paged_kv_cache_update

    pool = ctx.get_input(op, "Pool")
    new = ctx.get_input(op, "New")
    table = ctx.get_input(op, "PageTable")
    cache_len = ctx.get_input(op, "CacheLen")
    out, out_len = paged_kv_cache_update(pool, new, table, cache_len)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "OutLen", out_len)


@register("paged_multihead_attention_cache")
def _paged_multihead_attention_cache(ctx, op):
    """Decode-step attention against a PAGED KV cache
    (kernels/attention.py paged_attention_cache): gather-dense fallback
    or the Pallas paged tier (SMEM page table via scalar prefetch) at
    large capacities. Inference-only."""
    from ...kernels.attention import paged_attention_cache

    q = ctx.get_input(op, "Q")
    k_pool = ctx.get_input(op, "KPool")
    v_pool = ctx.get_input(op, "VPool")
    table = ctx.get_input(op, "PageTable")
    cache_len = ctx.get_input(op, "CacheLen")
    ctx.set_output(op, "Out", paged_attention_cache(
        q, k_pool, v_pool, table, cache_len,
        scale=op.attr("scale", None)))
