"""Control-flow op lowerings: cond, while, scan (StaticRNN).

Parity: reference ``operators/controlflow/conditional_block_op.cc``,
``while_op.cc:43`` (runs sub-block via a nested Executor), and
``recurrent_op.cc`` (static RNN). TPU-first: sub-blocks lower to pure
functions passed to ``lax.cond`` / ``lax.while_loop`` / ``lax.scan`` — traced
once and compiled into the same XLA program, instead of re-entering an
interpreter per iteration. Carried state is the set of sub-block-written
vars (the scope-mutation analogue, made explicit).
"""

import numpy as np

from ..registry import LowerCtx, lower_op, register, registry


def _block_writes(block):
    """Var names written by ops of a block (ordered, deduped)."""
    seen = []
    for op in block.ops:
        for n in op.output_arg_names():
            if n not in seen:
                seen.append(n)
    return seen


def _lower_subblock(ctx, block, env):
    sub = LowerCtx(block, env, ctx.rng_key, mesh=ctx.mesh)
    for op in block.ops:
        lower_op(sub, op)
    return env


@register("cond")
def _cond(ctx, op):
    import jax

    program = ctx.program
    pred = ctx.get_input(op, "Cond")
    true_idx = op.attr("true_block")
    false_idx = op.attr("false_block")
    true_outs = op.attr("true_outs")
    false_outs = op.attr("false_outs")
    out_names = op.attr("out_names")

    def make_branch(block_idx, branch_out_names):
        block = program.block(block_idx)

        def fn(env_snapshot):
            env = dict(env_snapshot)
            _lower_subblock(ctx, block, env)
            return [env[n] for n in branch_out_names]

        return fn

    snapshot = dict(ctx.env)
    pred_scalar = pred.reshape(()) if hasattr(pred, "reshape") else pred
    outs = jax.lax.cond(
        pred_scalar,
        make_branch(true_idx, true_outs),
        make_branch(false_idx, false_outs),
        snapshot,
    )
    for name, val in zip(out_names, outs):
        ctx.set(name, val)


@register("while")
def _while(ctx, op):
    """Reference while_op semantics: body block mutates vars (incl. the
    condition var); loop until condition is false. Carried state = all vars
    the body writes that already exist outside (+ the condition)."""
    import jax

    program = ctx.program
    block = program.block(op.attr("sub_block"))
    cond_name = op.input("Condition")[0]

    writes = _block_writes(block)
    carried = [n for n in writes if n in ctx.env]
    if cond_name not in carried:
        carried = [cond_name] + carried
    # side-bindings (@ALEN array lengths, @LOD lengths, @ROWS ids) of
    # carried vars ride along so their updates survive the loop
    for n in list(carried):
        for suf in ("@ALEN", "@LOD", "@ROWS"):
            key = n + suf
            if key in ctx.env and key not in carried:
                carried.append(key)

    init = tuple(ctx.env[n] for n in carried)
    cond_pos = carried.index(cond_name)
    snapshot = {k: v for k, v in ctx.env.items() if k not in carried}

    def cond_fun(carry):
        c = carry[cond_pos]
        return c.reshape(()) if hasattr(c, "reshape") else c

    def body_fun(carry):
        env = dict(snapshot)
        env.update(dict(zip(carried, carry)))
        _lower_subblock(ctx, block, env)
        return tuple(env[n] for n in carried)

    final = jax.lax.while_loop(cond_fun, body_fun, init)
    for n, v in zip(carried, final):
        ctx.set(n, v)


@register("static_rnn")
def _static_rnn(ctx, op):
    """StaticRNN (reference recurrent_op.cc) as lax.scan: sequence inputs
    scanned over time; memories carried; step outputs stacked."""
    import jax

    program = ctx.program
    block = program.block(op.attr("sub_block"))
    seq_inputs = op.attr("seq_inputs")  # outer names, (T, B, ...) time-major
    step_inputs = op.attr("step_inputs")  # per-step names inside block
    mem_init = op.attr("mem_init")  # outer names of initial memories
    mem_pre = op.attr("mem_pre")  # in-block pre-state names
    mem_post = op.attr("mem_post")  # in-block updated-state names
    step_outputs = op.attr("step_outputs")  # in-block per-step output names
    out_names = op.attr("out_names")  # outer stacked output names

    xs = tuple(ctx.get(n) for n in seq_inputs)
    init = tuple(ctx.get(n) for n in mem_init)
    snapshot = dict(ctx.env)

    def step(carry, x_t):
        env = dict(snapshot)
        env.update(dict(zip(mem_pre, carry)))
        env.update(dict(zip(step_inputs, x_t)))
        _lower_subblock(ctx, block, env)
        new_carry = tuple(env[n] for n in mem_post)
        outs = tuple(env[n] for n in step_outputs)
        return new_carry, outs

    final_carry, stacked = jax.lax.scan(step, init, xs)
    for n, v in zip(out_names, stacked):
        ctx.set(n, v)
    for outer, v in zip(op.attr("final_mem_names") or [], final_carry):
        ctx.set(outer, v)


# -- bounded TensorArray ------------------------------------------------------
#
# Reference LoDTensorArray (framework/lod_tensor_array.h, layers
# array_write/array_read at control_flow.py:1113/:1466) is a dynamically
# growing vector<LoDTensor>. XLA needs static shapes, so the TPU-native
# form follows the bounded-LoD recipe (fluid/lod.py): a fixed-capacity
# [bound, ...element] buffer plus an int32 length scalar side-bound to
# ``name + "@ALEN"``. Writes are functional dynamic-index updates (the
# autodiff replay differentiates straight through); reads are dynamic
# index gathers. Entries past the written length are zeros.

ALEN_SUFFIX = "@ALEN"


def _array_len(ctx, name):
    import jax.numpy as jnp

    key = name + ALEN_SUFFIX
    if key not in ctx.env:
        ctx.env[key] = jnp.zeros((), jnp.int32)
    return ctx.env[key]


@register("create_array")
def _create_array(ctx, op):
    import jax.numpy as jnp

    out = op.output("Out")[0]
    dtype = np.dtype(op.attr("dtype", "float32"))
    shp = op.attr("element_shape", None)
    bound = int(op.attr("bound", 0))
    if shp:
        ctx.set(out, jnp.zeros((bound,) + tuple(int(s) for s in shp),
                               dtype))
    else:
        # element shape unknown until the first write: 0-size sentinel
        # (arrays used inside While must pass element_shape so the loop
        # carry has its final shape from the start)
        ctx.set(out, jnp.zeros((0,), dtype))
    ctx.env[out + ALEN_SUFFIX] = jnp.zeros((), jnp.int32)


@register("array_write")
def _array_write(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    i = ctx.get_input(op, "I")
    name = op.output("Out")[0]
    arr = ctx.get(name)
    if arr.ndim == 1 and arr.shape[0] == 0:  # lazy sentinel
        bound = int(op.attr("bound", 0)) or 128
        arr = jnp.zeros((bound,) + x.shape, arr.dtype)
    i = jnp.reshape(i, ()).astype(jnp.int32)
    # out-of-bounds dynamic writes clamp to the last slot (XLA
    # dynamic_update_slice semantics) — size via create_array(bound=...)
    arr = jax.lax.dynamic_update_index_in_dim(
        arr, x.astype(arr.dtype), i, 0)
    ctx.set(name, arr)
    ctx.env[name + ALEN_SUFFIX] = jnp.maximum(_array_len(ctx, name), i + 1)


@register("array_read")
def _array_read(ctx, op):
    import jax
    import jax.numpy as jnp

    arr = ctx.get_input(op, "X")
    i = jnp.reshape(ctx.get_input(op, "I"), ()).astype(jnp.int32)
    ctx.set_output(op, "Out",
                   jax.lax.dynamic_index_in_dim(arr, i, 0, keepdims=False))


@register("array_length")
def _array_length(ctx, op):
    import jax.numpy as jnp

    name = op.input("X")[0]
    ctx.set_output(op, "Out", _array_len(ctx, name).reshape((1,)))


@register("tensor_array_to_tensor")
def _tensor_array_to_tensor(ctx, op):
    import jax.numpy as jnp

    arr = ctx.get_input(op, "X")           # [T, ...element]
    axis = int(op.attr("axis", 1))
    use_stack = bool(op.attr("use_stack", False))
    T = arr.shape[0]
    moved = jnp.moveaxis(arr, 0, axis)     # T at position `axis`
    if use_stack:
        out = moved                        # entries stacked along axis
        per_entry = arr.shape[1:][axis] if axis < arr.ndim - 1 else 1
    else:
        # concat along axis: merge (T, entry_axis) in T-major order.
        # Bounded semantics: ALL `bound` cells participate; unwritten
        # cells contribute zeros (exact reference match when the array
        # is fully written).
        shape = list(moved.shape)
        per_entry = shape[axis + 1]
        out = moved.reshape(tuple(shape[:axis]) + (T * per_entry,)
                            + tuple(shape[axis + 2:]))
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "OutIndex",
                   jnp.full((T,), per_entry, jnp.int32))
