"""Elementwise binary ops with fluid's axis-broadcast semantics, comparison
and logical ops.

Parity: reference ``operators/elementwise/`` (the broadcast engine
``elementwise_op_function.h``) and ``operators/controlflow/compare_op.cc``,
``logical_op.cc``. On TPU these all lower to VPU-vectorized XLA elementwise
HLOs and fuse into neighbors; no custom kernels needed.
"""

import numpy as np

from ..registry import register


def _broadcast_y(x, y, axis):
    """fluid semantics: align Y's dims to X starting at ``axis``; trailing
    dims of Y are matched, remaining X dims broadcast. axis=-1 means
    right-aligned (numpy) broadcasting."""
    import jax.numpy as jnp

    if axis is None or axis == -1 or x.ndim == y.ndim:
        return y
    # trim trailing size-1 dims of y (fluid allows e.g. y shape (N,1) vs axis=0)
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > 1:
        yshape.pop()
    new_shape = [1] * x.ndim
    for i, s in enumerate(yshape):
        new_shape[axis + i] = s
    return jnp.reshape(y, new_shape)


for _name in [
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "elementwise_mod",
    "elementwise_floordiv",
]:
    def _mk(name):
        @register(name)
        def _lower(ctx, op):
            import jax.numpy as jnp

            fns = {
                "elementwise_add": jnp.add,
                "elementwise_sub": jnp.subtract,
                "elementwise_mul": jnp.multiply,
                "elementwise_div": jnp.divide,
                "elementwise_max": jnp.maximum,
                "elementwise_min": jnp.minimum,
                "elementwise_pow": jnp.power,
                "elementwise_mod": jnp.mod,
                "elementwise_floordiv": jnp.floor_divide,
            }
            x = ctx.get_input(op, "X")
            y = ctx.get_input(op, "Y")
            y = _broadcast_y(x, y, op.attr("axis", -1))
            ctx.set_output(op, "Out", fns[name](x, y))

    _mk(_name)


# -- comparisons (outputs bool) --------------------------------------------

for _name, _attr in [
    ("less_than", "lt"),
    ("less_equal", "le"),
    ("greater_than", "gt"),
    ("greater_equal", "ge"),
    ("equal", "eq"),
    ("not_equal", "ne"),
]:
    def _mkc(name, kind):
        @register(name)
        def _lower(ctx, op):
            import jax.numpy as jnp

            fns = {
                "lt": jnp.less,
                "le": jnp.less_equal,
                "gt": jnp.greater,
                "ge": jnp.greater_equal,
                "eq": jnp.equal,
                "ne": jnp.not_equal,
            }
            x = ctx.get_input(op, "X")
            y = ctx.get_input(op, "Y")
            ctx.set_output(op, "Out", fns[kind](x, y))

    _mkc(_name, _attr)


# -- logical ---------------------------------------------------------------

@register("logical_and")
def _logical_and(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.logical_and(ctx.get_input(op, "X"), ctx.get_input(op, "Y")))


@register("logical_or")
def _logical_or(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.logical_or(ctx.get_input(op, "X"), ctx.get_input(op, "Y")))


@register("logical_xor")
def _logical_xor(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.logical_xor(ctx.get_input(op, "X"), ctx.get_input(op, "Y")))


@register("logical_not")
def _logical_not(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.logical_not(ctx.get_input(op, "X")))
