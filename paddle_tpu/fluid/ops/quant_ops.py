"""Fake-quantization op family — reference
``paddle/fluid/operators/fake_quantize_op.cc`` and
``fake_dequantize_op.cc`` (the kernels behind the slim QAT passes).

TPU-native design:
* Quant-dequant in training is the straight-through estimator expressed
  functionally: ``out = x + stop_gradient(qd(x) - x)``. The ``autodiff``
  replay then differentiates it as identity — no ``FakeQuantGradOp``
  registration needed (the reference synthesizes one per op).
* Scale state (moving averages, accumulators) are persistable scope vars
  threaded through the step function like optimizer accumulators — the
  in-place buffer mutation of the reference's CUDA kernels becomes buffer
  donation.
* Everything stays static-shape and fuses into the surrounding matmul —
  a fake-quant on a conv input is a handful of elementwise ops on the
  VPU, free next to the MXU work.
"""

import numpy as np

from ..registry import register


def _qrange(bits):
    return float((1 << (bits - 1)) - 1)  # 8 bits -> 127


def _quant_dequant(x, scale, qmax):
    """Symmetric uniform quant-dequant with straight-through gradient."""
    import jax
    import jax.numpy as jnp

    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    return x + jax.lax.stop_gradient(q - x)


@register("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(ctx, op):
    """Per-tensor dynamic abs-max quant-dequant (activations)."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    bits = int(op.attr("bit_length", 8))
    qmax = _qrange(bits)
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    ctx.set_output(op, "Out", _quant_dequant(x, scale, qmax))
    if op.output("OutScale"):
        ctx.set_output(op, "OutScale", scale.reshape(1))


@register("fake_channel_wise_quantize_dequantize_abs_max")
def _fake_qdq_channel_abs_max(ctx, op):
    """Per-output-channel abs-max quant-dequant (weights). ``quant_axis``
    picks the channel dim: 0 for conv filters [O,I,H,W], ndim-1 for
    mul/matmul weights [in, out]."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    bits = int(op.attr("bit_length", 8))
    axis = int(op.attr("quant_axis", 0)) % x.ndim
    qmax = _qrange(bits)
    reduce_dims = tuple(d for d in range(x.ndim) if d != axis)
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x), axis=reduce_dims))
    bshape = tuple(x.shape[d] if d == axis else 1 for d in range(x.ndim))
    ctx.set_output(op, "Out",
                   _quant_dequant(x, scale.reshape(bshape), qmax))
    if op.output("OutScale"):
        ctx.set_output(op, "OutScale", scale)


@register("fake_quantize_dequantize_moving_average_abs_max")
def _fake_qdq_moving_avg(ctx, op):
    """EMA-scale quant-dequant (reference FakeQuantOrWithDequantMovingAverageAbsMaxOp):
    state = state*rate + 1; accum = accum*rate + max|x|; scale = accum/state.
    ``is_test`` freezes the scale at InScale."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    in_scale = ctx.get_input(op, "InScale")
    bits = int(op.attr("bit_length", 8))
    rate = float(op.attr("moving_rate", 0.9))
    is_test = bool(op.attr("is_test", False))
    qmax = _qrange(bits)
    if is_test:
        scale = jnp.reshape(in_scale, ())
        ctx.set_output(op, "Out", _quant_dequant(x, scale, qmax))
        if op.output("OutScale"):
            ctx.set_output(op, "OutScale", jnp.reshape(scale, (1,)))
        return
    accum = ctx.get_input(op, "InAccum")
    state = ctx.get_input(op, "InState")
    cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    new_state = jnp.reshape(state, ()) * rate + 1.0
    new_accum = jnp.reshape(accum, ()) * rate + cur
    scale = new_accum / new_state
    ctx.set_output(op, "Out", _quant_dequant(x, scale, qmax))
    ctx.set_output(op, "OutScale", jnp.reshape(scale, (1,)))
    ctx.set_output(op, "OutAccum", jnp.reshape(new_accum, (1,)))
    ctx.set_output(op, "OutState", jnp.reshape(new_state, (1,)))


@register("moving_average_abs_max_scale")
def _moving_avg_scale(ctx, op):
    """Scale observer WITHOUT quantization (ScaleForTrainingPass): records
    the EMA abs-max of a var so inference knows its output threshold."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    rate = float(op.attr("moving_rate", 0.9))
    is_test = bool(op.attr("is_test", False))
    ctx.set_output(op, "Out", x)  # pass-through
    if is_test:
        if op.output("OutScale"):
            ctx.set_output(op, "OutScale",
                           jnp.reshape(ctx.get_input(op, "InScale"), (1,)))
        return
    accum = ctx.get_input(op, "InAccum")
    state = ctx.get_input(op, "InState")
    cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    new_state = jnp.reshape(state, ()) * rate + 1.0
    new_accum = jnp.reshape(accum, ()) * rate + cur
    ctx.set_output(op, "OutScale", jnp.reshape(new_accum / new_state, (1,)))
    ctx.set_output(op, "OutAccum", jnp.reshape(new_accum, (1,)))
    ctx.set_output(op, "OutState", jnp.reshape(new_state, (1,)))


@register("fake_quantize_range_abs_max")
def _fake_quant_range_abs_max(ctx, op):
    """Windowed running-max scale (reference FakeQuantizeRangeAbsMaxOp).
    TPU simplification: the scale is a running max that decays every
    ``window_size`` steps instead of a host-side scale history array —
    same steady-state behavior, no dynamic indexing."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    in_scale = ctx.get_input(op, "InScale")
    bits = int(op.attr("bit_length", 8))
    window = int(op.attr("window_size", 10000))
    is_test = bool(op.attr("is_test", False))
    qmax = _qrange(bits)
    if is_test:
        scale = jnp.reshape(in_scale, ())
        ctx.set_output(op, "Out", _quant_dequant(x, scale, qmax))
        if op.output("OutScale"):
            ctx.set_output(op, "OutScale", jnp.reshape(scale, (1,)))
        return
    it = ctx.get_input(op, "Iter")
    cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    prev = jnp.reshape(in_scale, ())
    itv = jnp.reshape(it, ()).astype(np.dtype("int32"))
    decay = (itv % window) == 0
    scale = jnp.where(decay, cur, jnp.maximum(prev, cur))
    ctx.set_output(op, "Out", _quant_dequant(x, scale, qmax))
    ctx.set_output(op, "OutScale", jnp.reshape(scale, (1,)))
    if op.output("OutIter"):
        ctx.set_output(op, "OutIter", jnp.reshape(itv + 1, (1,)))


@register("fake_quantize_abs_max")
def _fake_quant_abs_max(ctx, op):
    """Quantize ONLY (int values in a float container + scale) — the
    freeze-path op."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    bits = int(op.attr("bit_length", 8))
    qmax = _qrange(bits)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-9)
    ctx.set_output(op, "Out",
                   jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax))
    ctx.set_output(op, "OutScale", scale.reshape(1))


@register("fake_dequantize_max_abs")
def _fake_dequant_max_abs(ctx, op):
    """out = x * scale / max_range (reference fake_dequantize_op.cc)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    scale = ctx.get_input(op, "Scale")
    max_range = float(op.attr("max_range", 127.0))
    ctx.set_output(op, "Out",
                   x.astype(np.dtype("float32")) *
                   jnp.reshape(scale, ()) / max_range)


@register("fake_channel_wise_dequantize_max_abs")
def _fake_channel_wise_dequant(ctx, op):
    """Two-level channel-wise dequant: Scales = [weight_scales(per-channel),
    activation_scale(optional)] (reference fake_dequantize_op.cc:
    FakeChannelWiseDequantizeMaxAbsOp)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    scale_names = op.input("Scales")
    bits = [int(b) for b in op.attr("quant_bits", [8, 8])]
    wscale = ctx.get(scale_names[0])
    out = x.astype(np.dtype("float32"))
    # quant_axis: the op-OUTPUT dim the weight channels land on (last dim
    # for mul/matmul, dim 1 for NCHW conv); default keeps the shape-match
    # heuristic for single-scale tensors
    axis = op.attr("quant_axis", None)
    if axis is None:
        axis = out.ndim - 1 if (out.ndim >= 2 and
                                wscale.shape[0] == out.shape[-1]) else 0
    axis = int(axis) % out.ndim
    bshape = tuple(-1 if d == axis else 1 for d in range(out.ndim))
    out = out * wscale.reshape(bshape) / _qrange(bits[0])
    if len(scale_names) > 1:
        ascale = ctx.get(scale_names[1])
        out = out * jnp.reshape(ascale, ()) / _qrange(bits[1])
    ctx.set_output(op, "Out", out)
