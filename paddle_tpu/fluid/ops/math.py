"""Dense math ops: mul/matmul (MXU), reductions, scale/clip, top-k, argsort.

Parity: reference ``operators/mul_op.cc``, ``matmul_op.cc``,
``reduce_ops/``, ``scale_op.cc``, ``clip_op.cc``, ``top_k_op.cc``,
``arg_{max,min}_op``, ``argsort_op.cc``, ``sum_op.cc``, ``mean_op.cc``.

Matmuls keep their natural (large, batched) shapes so XLA tiles them onto
the 128x128 MXU; no manual blocking.
"""

import numpy as np

from ..registry import register


@register("mul")
def _mul(ctx, op):
    """Reference mul_op: flatten x to 2-D by x_num_col_dims, y by
    y_num_col_dims, then matmul."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    xd = op.attr("x_num_col_dims", 1)
    yd = op.attr("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = jnp.reshape(x, (int(np.prod(xs[:xd])), -1))
    y2 = jnp.reshape(y, (int(np.prod(ys[:yd])), -1))
    out = x2 @ y2
    out_shape = xs[:xd] + ys[yd:]
    ctx.set_output(op, "Out", jnp.reshape(out, out_shape))


@register("matmul")
def _matmul(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    tx, ty = op.attr("transpose_X", False), op.attr("transpose_Y", False)
    alpha = op.attr("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_output(op, "Out", out)


@register("bmm")
def _bmm(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.matmul(ctx.get_input(op, "X"), ctx.get_input(op, "Y")))


@register("sum")
def _sum(ctx, op):
    xs = ctx.get_inputs(op, "X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output(op, "Out", out)


@register("mean")
def _mean(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.mean(ctx.get_input(op, "X")))


@register("scale")
def _scale(ctx, op):
    x = ctx.get_input(op, "X")
    scale = op.attr("scale", 1.0)
    bias = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    # scale preserves input dtype (reference scale_op semantics)
    ctx.set_output(op, "Out", out.astype(x.dtype))


@register("clip")
def _clip(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.clip(x, op.attr("min"), op.attr("max")))


@register("clip_by_norm")
def _clip_by_norm(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    max_norm = op.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    ctx.set_output(op, "Out", jnp.where(norm > max_norm, x * (max_norm / norm), x))


def _reduce(name, jfn):
    @register(name)
    def _lower(ctx, op):
        x = ctx.get_input(op, "X")
        dim = op.attr("dim", [0])
        keep = op.attr("keep_dim", False)
        if op.attr("reduce_all", False):
            axes = None
        else:
            axes = tuple(d if d >= 0 else d + x.ndim for d in (dim if isinstance(dim, (list, tuple)) else [dim]))
        ctx.set_output(op, "Out", jfn(x, axes, keep))


def _jnp_reduce(fname):
    def fn(x, axes, keep):
        import jax.numpy as jnp

        f = getattr(jnp, fname)
        return f(x, axis=axes, keepdims=keep)

    return fn


for _n, _f in [
    ("reduce_sum", "sum"),
    ("reduce_mean", "mean"),
    ("reduce_max", "max"),
    ("reduce_min", "min"),
    ("reduce_prod", "prod"),
    ("reduce_all", "all"),
    ("reduce_any", "any"),
]:
    _reduce(_n, _jnp_reduce(_f))


@register("top_k")
def _top_k(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    k = op.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    ctx.set_output(op, "Out", vals)
    ctx.set_output(op, "Indices", idx.astype(np.dtype("int64")))


@register("arg_max")
def _arg_max(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    ctx.set_output(op, "Out", jnp.argmax(x, axis=axis).astype(np.dtype("int64")))


@register("arg_min")
def _arg_min(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    ctx.set_output(op, "Out", jnp.argmin(x, axis=axis).astype(np.dtype("int64")))


@register("argsort")
def _argsort(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    descending = op.attr("descending", False)
    idx = jnp.argsort(-x if descending else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Indices", idx.astype(np.dtype("int64")))


@register("l2_normalize")
def _l2_normalize(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", -1)
    eps = op.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    ctx.set_output(op, "Out", x / jnp.maximum(norm, eps))
    ctx.set_output(op, "Norm", norm)


@register("cos_sim")
def _cos_sim(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    ctx.set_output(op, "Out", out)


@register("isfinite")
def _isfinite(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    ctx.set_output(op, "Out", jnp.all(jnp.isfinite(x)))


@register("has_inf")
def _has_inf(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.any(jnp.isinf(ctx.get_input(op, "X"))))


@register("has_nan")
def _has_nan(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.any(jnp.isnan(ctx.get_input(op, "X"))))


@register("maxout")
def _maxout(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # NCHW
    groups = op.attr("groups")
    n, c, h, w = x.shape
    out = jnp.max(jnp.reshape(x, (n, c // groups, groups, h, w)), axis=2)
    ctx.set_output(op, "Out", out)


@register("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # (B, M)
    y = ctx.get_input(op, "Y")  # (B, N)
    w = ctx.get_input(op, "Weight")  # (out, M, N)
    bias = ctx.get_input(op, "Bias")
    out = jnp.einsum("bm,omn,bn->bo", x, w, y)
    if bias is not None:
        out = out + bias
    ctx.set_output(op, "Out", out)


@register("einsum")
def _einsum(ctx, op):
    """General tensor contraction by equation (the ``paddle.einsum``
    capability; lowered directly to jnp.einsum so XLA picks operand
    layouts — e.g. attention scores from the fc-native [B, S, H, d]
    layout without materialized head transposes)."""
    import jax.numpy as jnp

    xs = ctx.get_inputs(op, "Operands")
    ctx.set_output(op, "Out", jnp.einsum(op.attr("equation"), *xs))
