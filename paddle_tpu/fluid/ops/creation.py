"""Tensor-creation and random ops.

Parity: reference ``operators/fill_constant_op.cc``, ``uniform_random_op.cc``,
``gaussian_random_op.cc``, ``truncated_gaussian_random_op.cc``,
``assign_value_op.cc``, ``range_op.cc``, ``linspace_op.cc``, ``eye_op`` /
``diag_op.cc``. Randomness is functional: each op draws a key from the
threaded PRNG stream (see ``registry.LowerCtx.next_rng``).
"""

import os

import numpy as np

from ..registry import register


def _shape_attr(ctx, op):
    shape = op.attr("shape")
    return tuple(int(s) for s in shape)


@register("fill_constant")
def _fill_constant(ctx, op):
    import jax.numpy as jnp

    dtype = np.dtype(op.attr("dtype", "float32"))
    value = op.attr("value", 0.0)
    shape = _shape_attr(ctx, op)
    ctx.set_output(op, "Out", jnp.full(shape, value, dtype=dtype))


@register("fill_constant_batch_size_like")
def _fill_constant_bsl(ctx, op):
    import jax.numpy as jnp

    ref = ctx.get_input(op, "Input")
    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = list(_shape_attr(ctx, op))
    in_idx = op.attr("input_dim_idx", 0)
    out_idx = op.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    ctx.set_output(op, "Out", jnp.full(tuple(shape), op.attr("value", 0.0), dtype=dtype))


@register("uniform_random", has_state=True)
def _uniform_random(ctx, op):
    import jax

    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = _shape_attr(ctx, op)
    lo, hi = op.attr("min", -1.0), op.attr("max", 1.0)
    out = jax.random.uniform(ctx.next_rng(), shape, minval=lo, maxval=hi, dtype=jax.numpy.float32)
    ctx.set_output(op, "Out", out.astype(dtype))


@register("uniform_random_batch_size_like", has_state=True)
def _uniform_random_bsl(ctx, op):
    import jax

    ref = ctx.get_input(op, "Input")
    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = list(_shape_attr(ctx, op))
    shape[op.attr("output_dim_idx", 0)] = ref.shape[op.attr("input_dim_idx", 0)]
    out = jax.random.uniform(
        ctx.next_rng(), tuple(shape), minval=op.attr("min", -1.0), maxval=op.attr("max", 1.0)
    )
    ctx.set_output(op, "Out", out.astype(dtype))


@register("gaussian_random", has_state=True)
def _gaussian_random(ctx, op):
    import jax
    import jax.numpy as jnp

    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = _shape_attr(ctx, op)
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    out = mean + std * jax.random.normal(ctx.next_rng(), shape, dtype=jnp.float32)
    ctx.set_output(op, "Out", out.astype(dtype))


@register("gaussian_random_batch_size_like", has_state=True)
def _gaussian_random_bsl(ctx, op):
    import jax
    import jax.numpy as jnp

    ref = ctx.get_input(op, "Input")
    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = list(_shape_attr(ctx, op))
    shape[op.attr("output_dim_idx", 0)] = ref.shape[op.attr("input_dim_idx", 0)]
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.normal(
        ctx.next_rng(), tuple(shape), dtype=jnp.float32
    )
    ctx.set_output(op, "Out", out.astype(dtype))


@register("truncated_gaussian_random", has_state=True)
def _truncated_gaussian_random(ctx, op):
    import jax
    import jax.numpy as jnp

    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = _shape_attr(ctx, op)
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    out = jax.random.truncated_normal(ctx.next_rng(), -2.0, 2.0, shape, dtype=jnp.float32)
    ctx.set_output(op, "Out", (mean + std * out).astype(dtype))


@register("randint", has_state=True)
def _randint(ctx, op):
    import jax

    dtype = np.dtype(op.attr("dtype", "int64"))
    shape = _shape_attr(ctx, op)
    out = jax.random.randint(ctx.next_rng(), shape, op.attr("low", 0), op.attr("high", 1))
    ctx.set_output(op, "Out", out.astype(dtype))


@register("sampling_id", has_state=True)
def _sampling_id(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    out = jax.random.categorical(ctx.next_rng(), jax.numpy.log(x + 1e-20), axis=-1)
    ctx.set_output(op, "Out", out.astype(np.dtype("int64")))


@register("assign_value")
def _assign_value(ctx, op):
    import jax.numpy as jnp

    dtype = np.dtype(op.attr("dtype", "float32"))
    shape = _shape_attr(ctx, op)
    values = op.attr("values")
    ctx.set_output(op, "Out", jnp.asarray(values, dtype=dtype).reshape(shape))


# (path) -> (mtime, size, array): one load op is lowered at least twice
# (build-time shape inference under eval_shape, then the executor's jit
# trace) — memoizing by file identity avoids re-reading a potentially
# multi-GB tensor file, while an mtime/size change (file rewritten
# between build and run) still triggers a fresh read. Bounded: entries
# evict once consumed by a newer path.
_LOAD_CACHE = {}
_LOAD_CACHE_MAX = 4


def _read_tensor_file(path):
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    hit = _LOAD_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    with open(path, "rb") as f:
        magic = f.read(4)
    if magic in (b"PTC1", b"PK\x03\x04"):        # native serde / npz
        from ..io import _load_combined

        entries = _load_combined(path)
        if len(entries) != 1:
            raise ValueError(
                "layers.load expects ONE tensor in %r, found %d "
                "(use fluid.io.load_vars for combined files)"
                % (path, len(entries)))
        (arr,) = entries.values()
    else:
        arr = np.load(path, allow_pickle=False)   # plain .npy
    while len(_LOAD_CACHE) >= _LOAD_CACHE_MAX:
        _LOAD_CACHE.pop(next(iter(_LOAD_CACHE)))
    _LOAD_CACHE[path] = (key, arr)
    return arr


@register("load")
def _load_tensor_file(ctx, op):
    """Reference ``load_op.cc``: read one tensor from disk into Out.
    TPU design: the read happens at lowering (trace) time, so the value
    enters the compiled step as a constant — create the file BEFORE
    building/running the program (the op's canonical home is a startup
    program, which runs once)."""
    import jax.numpy as jnp

    path = op.attr("file_path")
    if not os.path.exists(path):
        raise FileNotFoundError(
            "layers.load: tensor file %r does not exist at lowering "
            "time (write it before building/running the program)" % path)
    arr = _read_tensor_file(path)
    if op.attr("load_as_fp16", False):
        arr = np.asarray(arr, np.float16)
    ctx.set_output(op, "Out", jnp.asarray(arr))


@register("save")
def _save_tensor_file(ctx, op):
    """Reference ``save_op.cc`` capability: persist X to file_path. TPU
    deviation: the whole block is ONE compiled step, so the Executor
    performs the write AFTER the step commits — the file always holds
    the post-step value regardless of the op's position, and only
    persistable vars are saveable (executor.py run). The lowering is a
    no-op pass-through so programs containing save ops compile."""


@register("range")
def _range(ctx, op):
    import jax.numpy as jnp

    # XLA needs static shapes: bounds come as attrs (python scalars); a
    # Variable bound resolves statically through its producing
    # assign_value/fill_constant op (everything in the traced block is a
    # Tracer, so runtime values can't size the output)
    def _static_bound(name):
        for o in ctx.block.ops:
            if name in o.output_arg_names():
                if o.type == "assign_value":
                    return float(np.asarray(o.attr("values")).ravel()[0])
                if o.type == "fill_constant":
                    return float(o.attr("value"))
        return None

    vals = []
    for slot, attr in (("Start", "start"), ("End", "end"), ("Step", "step")):
        v = op.attr(attr)
        if v is None:
            names = op.input(slot)
            v = _static_bound(names[0]) if names else None
            if v is None:
                raise NotImplementedError(
                    "range bounds must be python scalars or "
                    "assign_value/fill_constant Variables — a "
                    "runtime-variable bound cannot have a static shape")
        vals.append(v)
    dtype = np.dtype(op.attr("dtype", "float32"))
    ctx.set_output(op, "Out", jnp.arange(*vals, dtype=dtype))


@register("linspace")
def _linspace(ctx, op):
    import jax.numpy as jnp

    start = ctx.get_input(op, "Start")
    stop = ctx.get_input(op, "Stop")
    num = op.attr("num")
    if num is None:
        num = int(np.asarray(ctx.get_input(op, "Num")))
    ctx.set_output(op, "Out", jnp.linspace(jnp.reshape(start, ()), jnp.reshape(stop, ()), int(num)))


@register("eye")
def _eye(ctx, op):
    import jax.numpy as jnp

    dtype = np.dtype(op.attr("dtype", "float32"))
    ctx.set_output(
        op, "Out", jnp.eye(op.attr("num_rows"), op.attr("num_columns"), dtype=dtype)
    )


@register("diag")
def _diag(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "Diagonal")
    ctx.set_output(op, "Out", jnp.diag(x))
