"""Collective communication ops.

Parity: reference ``operators/collective/`` (c_allreduce_{sum,max,min,prod},
c_broadcast, c_allgather, c_reducescatter, c_sync_*_stream — SURVEY §2.6).

TPU-native: ``ring_id`` maps to a *named mesh axis* (ring 0 → first axis).
Under ``shard_map`` these lower to XLA collectives over ICI
(psum/all_gather/psum_scatter/pbroadcast); outside any mesh context they are
identity (single-rank world), matching reference behavior with one trainer.
Stream-sync ops are no-ops: XLA orders collectives by dataflow.
"""

from ..registry import register


def _axis_for(ctx, op):
    """ring_id -> mesh axis name. Under shard_map, LowerCtx.shard_axes holds
    the active axis names."""
    axes = getattr(ctx, "shard_axes", None)
    if not axes:
        return None
    ring = op.attr("ring_id", 0)
    return axes[min(ring, len(axes) - 1)]


def _allreduce(kind):
    def lower(ctx, op):
        import jax

        x = ctx.get_input(op, "X")
        axis = _axis_for(ctx, op)
        if axis is None:
            out = x
        elif kind == "sum":
            out = jax.lax.psum(x, axis)
        elif kind == "max":
            out = jax.lax.pmax(x, axis)
        elif kind == "min":
            out = jax.lax.pmin(x, axis)
        elif kind == "prod":
            import jax.numpy as jnp

            # XLA has no product all-reduce primitive; all_gather + prod is
            # exact for zeros and negatives (exp(psum(log)) is not)
            out = jnp.prod(jax.lax.all_gather(x, axis), axis=0)
        elif kind == "avg":
            out = jax.lax.pmean(x, axis)
        ctx.set_output(op, "Out", out)

    return lower


for _k in ("sum", "max", "min", "prod", "avg"):
    register("c_allreduce_%s" % _k, _allreduce(_k))
register("allreduce", _allreduce("sum"))  # dygraph DP op


@register("c_broadcast")
def _c_broadcast(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    axis = _axis_for(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    root = op.attr("root", 0)
    # broadcast = select root shard then replicate (all_gather + take)
    gathered = jax.lax.all_gather(x, axis)
    ctx.set_output(op, "Out", gathered[root])


@register("c_allgather")
def _c_allgather(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = _axis_for(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    gathered = jax.lax.all_gather(x, axis)  # (nranks, ...)
    ctx.set_output(op, "Out", jnp.reshape(gathered, (-1,) + tuple(x.shape[1:])))


@register("c_hierarchical_allreduce")
def _c_hierarchical_allreduce(ctx, op):
    """Hierarchical allreduce (reference ``use_hierarchical_allreduce``):
    on a 2-level ``(host, device)`` mesh the gradient reduce-scatters and
    all-gathers inside a host (ICI, axes[1]) and only the 1/D shard
    crosses hosts (DCN, axes[0] — the outermost/slowest axis). On a
    single-axis mesh this degrades to a flat psum; with no mesh it is
    identity — so the transpiler can emit it unconditionally."""
    import jax

    x = ctx.get_input(op, "X")
    axes = getattr(ctx, "shard_axes", None)
    if not axes:
        ctx.set_output(op, "Out", x)
        return
    if len(axes) < 2:
        ctx.set_output(op, "Out", jax.lax.psum(x, axes[0]))
        return
    from ...parallel.cross_host import hier_psum

    ctx.set_output(op, "Out", hier_psum(x, host_axis=axes[0],
                                        device_axis=axes[1]))


@register("c_reducescatter")
def _c_reducescatter(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    axis = _axis_for(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    ctx.set_output(op, "Out", jax.lax.psum_scatter(x, axis, tiled=True))


@register("c_concat")
def _c_concat(ctx, op):
    _c_allgather(ctx, op)


@register("collective_permute")
def _collective_permute(ctx, op):
    """Ring permute (ring-attention building block). attrs: shift (default 1,
    neighbor exchange over the axis ring)."""
    import jax

    x = ctx.get_input(op, "X")
    axis = _axis_for(ctx, op)
    if axis is None:
        ctx.set_output(op, "Out", x)
        return
    n = getattr(ctx, "shard_sizes", {}).get(axis)
    shift = op.attr("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    ctx.set_output(op, "Out", jax.lax.ppermute(x, axis, perm))


@register("c_sync_calc_stream")
@register("c_sync_comm_stream")
def _c_sync(ctx, op):
    # XLA schedules collectives by dataflow; explicit stream sync is a no-op.
    names = op.input("X")
    for n, o in zip(names, op.output("Out")):
        ctx.set(o, ctx.get(n))


@register("c_gen_nccl_id")
@register("gen_nccl_id")
def _c_gen_nccl_id(ctx, op):
    # Bootstrap handled by the JAX coordination service (jax.distributed);
    # nothing to materialize in-graph.
    pass


@register("c_comm_init")
@register("c_comm_init_all")
def _c_comm_init(ctx, op):
    pass


@register("barrier")
def _barrier(ctx, op):
    import jax

    axis = _axis_for(ctx, op)
    if op.input("X"):
        x = ctx.get_input(op, "X")
        if axis is not None:
            # psum of zeros = synchronization point
            x = x + 0 * jax.lax.psum(x * 0, axis)
        ctx.set_output(op, "Out", x)  # single-rank: identity


@register("shard_tensor")
def _shard_tensor(ctx, op):
    """Activation sharding hint: lax.with_sharding_constraint under the
    active mesh (identity otherwise). The TPU-native sequence/tensor-
    parallel annotation — attrs: spec = [axis-name-or-None per dim]."""
    x = ctx.get_input(op, "X")
    mesh = getattr(ctx, "mesh", None)
    # identity without a mesh, AND under shard_map (explicit-collective
    # mode sets ctx.shard_axes): inside shard_map the axes are manual and a
    # global sharding constraint on a per-shard value is ill-formed
    if mesh is None or getattr(ctx, "shard_axes", None):
        ctx.set_output(op, "Out", x)
        return
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    spec = [None if s in (None, "", "None") else s
            for s in op.attr("spec", [])]
    out = jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))
    ctx.set_output(op, "Out", out)
