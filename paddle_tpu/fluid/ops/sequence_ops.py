"""Sequence (LoD) op lowerings — the reference's ``operators/sequence_ops/``
(~16 ops, 5.8k LoC of CPU/CUDA kernels over ragged LoDTensors).

TPU-native: inputs are bounded-LoD pairs (flattened ``[total_bound, ...]``
data + ``name@LOD`` int32 lengths — see ``fluid/lod.py``). Every op reduces
to static-shape segment arithmetic:

    cum  = cumsum(lengths)               # [n]
    seg  = searchsorted(cum, arange(T))  # token -> sequence id, pads get n
    pos  = arange(T) - starts[seg]       # position within the sequence

Padding rows (token index >= sum(lengths)) fall out of range and are dropped
by ``segment_sum``/masked by ``where`` — no dynamic shapes anywhere, so XLA
tiles everything onto the vector/matrix units and lengths can change per
batch without recompilation. This file is the designed replacement for the
reference's ragged kernels (SURVEY §7 "hard parts": padding/bucketing
strategy), not a port of them.
"""

import numpy as np

from ..registry import register


def _lod(ctx, name):
    from ..lod import lod_name

    key = lod_name(name)
    if key not in ctx.env:
        raise KeyError(
            "%r has no @LOD lengths binding; feed it as fluid.create_lod_tensor"
            " or produce it with a sequence op" % name)
    return ctx.env[key]


def _seg_info(lengths, total):
    import jax.numpy as jnp

    lengths = lengths.astype(np.dtype("int32"))
    cum = jnp.cumsum(lengths)
    tok = jnp.arange(total, dtype=np.dtype("int32"))
    seg = jnp.searchsorted(cum, tok, side="right").astype(np.dtype("int32"))
    starts = jnp.concatenate(
        [jnp.zeros((1,), np.dtype("int32")), cum[:-1]])
    valid = tok < cum[-1]
    return seg, starts, cum, valid


def _set_lod(ctx, op, slot, lengths):
    from ..lod import lod_name

    names = op.output(slot)
    if names:
        ctx.env[lod_name(names[0])] = lengths


@register("sequence_pool")
def _sequence_pool(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    lengths = _lod(ctx, op.input("X")[0])
    n = lengths.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, x.shape[0])
    ptype = str(op.attr("pooltype", "AVERAGE")).upper()
    pad_value = float(op.attr("pad_value", 0.0))
    empty = (lengths == 0)
    if ptype in ("SUM", "AVERAGE", "SQRT"):
        out = jax.ops.segment_sum(x, seg, num_segments=n)
        denom = jnp.maximum(lengths, 1).astype(x.dtype)
        if ptype == "AVERAGE":
            out = out / denom[:, None]
        elif ptype == "SQRT":
            out = out / jnp.sqrt(denom)[:, None]
    elif ptype == "MAX":
        out = jax.ops.segment_max(x, seg, num_segments=n)
        out = jnp.where(empty[:, None], 0.0, out)
        if op.output("MaxIndex"):
            # argmax within segment: first token index achieving the max
            is_max = (x == out[jnp.clip(seg, 0, n - 1)]) & valid[:, None]
            tok = jnp.arange(x.shape[0], dtype=np.dtype("int32"))[:, None]
            big = jnp.where(is_max, tok, x.shape[0])
            idx = jax.ops.segment_min(
                jnp.broadcast_to(big, x.shape), seg, num_segments=n)
            ctx.set_output(op, "MaxIndex", idx.astype(np.dtype("int32")))
    elif ptype == "FIRST":
        out = x[jnp.clip(starts, 0, x.shape[0] - 1)]
    elif ptype == "LAST":
        out = x[jnp.clip(cum - 1, 0, x.shape[0] - 1)]
    else:
        raise NotImplementedError("sequence_pool type %r" % ptype)
    out = jnp.where(empty[:, None], jnp.asarray(pad_value, x.dtype), out)
    ctx.set_output(op, "Out", out.astype(x.dtype))


@register("sequence_softmax")
def _sequence_softmax(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    lengths = _lod(ctx, op.input("X")[0])
    n = lengths.shape[0]
    x1 = x.reshape(x.shape[0], -1)
    seg, starts, cum, valid = _seg_info(lengths, x.shape[0])
    neg = jnp.asarray(-1e30, x1.dtype)
    xm = jnp.where(valid[:, None], x1, neg)
    m = jax.ops.segment_max(xm, seg, num_segments=n)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    e = jnp.exp(x1 - m[jnp.clip(seg, 0, n - 1)]) * valid[:, None].astype(x1.dtype)
    s = jax.ops.segment_sum(e, seg, num_segments=n)
    s = jnp.maximum(s, 1e-30)
    out = (e / s[jnp.clip(seg, 0, n - 1)]).reshape(x.shape)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", lengths)


@register("sequence_reverse")
def _sequence_reverse(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    lengths = _lod(ctx, op.input("X")[0])
    seg, starts, cum, valid = _seg_info(lengths, x.shape[0])
    tok = jnp.arange(x.shape[0], dtype=np.dtype("int32"))
    idx = starts[jnp.clip(seg, 0, lengths.shape[0] - 1)] + \
        cum[jnp.clip(seg, 0, lengths.shape[0] - 1)] - 1 - tok
    idx = jnp.clip(idx, 0, x.shape[0] - 1)
    out = jnp.where(valid.reshape((-1,) + (1,) * (x.ndim - 1)), x[idx], 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", lengths)


@register("sequence_expand")
def _sequence_expand(ctx, op):
    """x rows (one per ref sequence, or lod level-1) repeated to match y's
    token layout (reference sequence_expand_op.cc, ref_level semantics for
    the common x-lod-level-0 case)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y_name = op.input("Y")[0]
    ylen = _lod(ctx, y_name)
    y = ctx.get(y_name)
    n = ylen.shape[0]
    seg, starts, cum, valid = _seg_info(ylen, y.shape[0])
    from ..lod import lod_name

    xlod_key = lod_name(op.input("X")[0])
    if xlod_key in ctx.env:
        # x ragged: repeat each x *sequence* to y's slot — general case
        xlen = ctx.env[xlod_key]
        xseg, xstarts, xcum, xvalid = _seg_info(xlen, x.shape[0])
        tok = jnp.arange(y.shape[0], dtype=np.dtype("int32"))
        pos = tok - starts[jnp.clip(seg, 0, n - 1)]
        src = xstarts[jnp.clip(seg, 0, n - 1)] + pos
        src = jnp.clip(src, 0, x.shape[0] - 1)
        out = jnp.where(valid.reshape((-1,) + (1,) * (x.ndim - 1)),
                        x[src], 0)
    else:
        # x dense [n, D]: broadcast row i over y's i-th sequence tokens
        src = jnp.clip(seg, 0, n - 1)
        out = jnp.where(valid.reshape((-1,) + (1,) * (x.ndim - 1)),
                        x[src], 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", ylen)


@register("sequence_expand_as")
def _sequence_expand_as(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y_name = op.input("Y")[0]
    ylen = _lod(ctx, y_name)
    y = ctx.get(y_name)
    n = ylen.shape[0]
    seg, starts, cum, valid = _seg_info(ylen, y.shape[0])
    src = jnp.clip(seg, 0, n - 1)
    out = jnp.where(valid.reshape((-1,) + (1,) * (x.ndim - 1)), x[src], 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", ylen)


@register("sequence_pad")
def _sequence_pad(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    pad_value = ctx.get_input(op, "PadValue")
    lengths = _lod(ctx, op.input("X")[0])
    n = lengths.shape[0]
    maxlen = int(op.attr("padded_length", -1))
    if maxlen <= 0:
        maxlen = int(x.shape[0])  # physical bound = worst case
    seg, starts, cum, valid = _seg_info(lengths, x.shape[0])
    feat = x.shape[1:]
    pad = jnp.broadcast_to(jnp.asarray(pad_value, x.dtype).reshape(
        (1, 1) + (1,) * len(feat)), (n, maxlen) + feat)
    # gather layout: out[i, p] = x[starts[i] + p] where p < len[i]
    grid_pos = jnp.arange(maxlen, dtype=np.dtype("int32"))[None, :]
    src = starts[:, None] + grid_pos  # [n, maxlen]
    src = jnp.clip(src, 0, x.shape[0] - 1)
    inb = grid_pos < jnp.minimum(lengths, maxlen)[:, None]
    gathered = x[src]  # [n, maxlen, ...]
    out = jnp.where(inb.reshape((n, maxlen) + (1,) * len(feat)),
                    gathered, pad)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    if op.output("Length"):
        ctx.set_output(op, "Length",
                       jnp.minimum(lengths, maxlen).astype(np.dtype("int64")))


@register("sequence_unpad")
def _sequence_unpad(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # [n, maxlen, ...]
    length = ctx.get_input(op, "Length").astype(np.dtype("int32"))
    length = length.reshape(-1)
    n, maxlen = x.shape[0], x.shape[1]
    total = n * maxlen
    seg, starts, cum, valid = _seg_info(length, total)
    tok = jnp.arange(total, dtype=np.dtype("int32"))
    pos = tok - starts[jnp.clip(seg, 0, n - 1)]
    srcseq = jnp.clip(seg, 0, n - 1)
    srcpos = jnp.clip(pos, 0, maxlen - 1)
    out = jnp.where(valid.reshape((-1,) + (1,) * (x.ndim - 2)),
                    x[srcseq, srcpos], 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", length)


@register("sequence_mask")
def _sequence_mask(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X").reshape(-1)
    maxlen = op.attr("maxlen", -1)
    if maxlen is None or int(maxlen) <= 0:
        mv = ctx.get_input(op, "MaxLenTensor")
        try:
            maxlen = int(mv) if mv is not None else None
        except Exception:
            maxlen = None  # traced value — not static
        if maxlen is None:
            raise ValueError(
                "sequence_mask needs a compile-time-constant maxlen on TPU "
                "(a fed/computed MaxLenTensor or max(lengths) would be a "
                "dynamic output shape, which XLA cannot compile)")
    maxlen = int(maxlen)
    dtype = np.dtype(op.attr("out_dtype", "int64"))
    out = (jnp.arange(maxlen, dtype=x.dtype)[None, :] < x[:, None])
    ctx.set_output(op, "Out", out.astype(dtype))


@register("sequence_reshape")
def _sequence_reshape(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    lengths = _lod(ctx, op.input("X")[0])
    new_dim = int(op.attr("new_dim"))
    d = int(np.prod(x.shape[1:]))
    out = jnp.reshape(x, (-1, new_dim))
    new_len = (lengths * d) // new_dim
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", new_len.astype(np.dtype("int32")))


@register("sequence_concat")
def _sequence_concat(ctx, op):
    """Interleave: out sequence i = concat_k(input_k sequence i)."""
    import jax.numpy as jnp

    names = op.input("X")
    xs = [ctx.get(nm) for nm in names]
    lens = [_lod(ctx, nm).astype(np.dtype("int32")) for nm in names]
    n = lens[0].shape[0]
    out_len = sum(lens)
    outT = int(sum(x.shape[0] for x in xs))
    feat = xs[0].shape[1:]
    oseg, ostarts, ocum, _ = _seg_info(out_len, outT)
    out = jnp.zeros((outT,) + feat, xs[0].dtype)
    # offset of input k's tokens inside out-sequence = sum of lens[<k]
    run = jnp.zeros((n,), np.dtype("int32"))
    for x, ln in zip(xs, lens):
        seg, starts, cum, valid = _seg_info(ln, x.shape[0])
        tok = jnp.arange(x.shape[0], dtype=np.dtype("int32"))
        pos = tok - starts[jnp.clip(seg, 0, n - 1)]
        dst = ostarts[jnp.clip(seg, 0, n - 1)] + \
            run[jnp.clip(seg, 0, n - 1)] + pos
        dst = jnp.where(valid, dst, outT)  # dropped
        out = out.at[dst].set(
            jnp.where(valid.reshape((-1,) + (1,) * len(feat)), x, 0),
            mode="drop")
        run = run + ln
    ctx.set_output(op, "Out", out)
    _set_lod(ctx, op, "Out", out_len)


@register("sequence_slice")
def _sequence_slice(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    offset = ctx.get_input(op, "Offset").astype(np.dtype("int32")).reshape(-1)
    length = ctx.get_input(op, "Length").astype(np.dtype("int32")).reshape(-1)
    lengths = _lod(ctx, op.input("X")[0])
    n = lengths.shape[0]
    seg_i, starts_i, _, _ = _seg_info(lengths, x.shape[0])
    # output keeps the physical bound; logical lengths = requested lengths
    oseg, ostarts, ocum, ovalid = _seg_info(length, x.shape[0])
    tok = jnp.arange(x.shape[0], dtype=np.dtype("int32"))
    pos = tok - ostarts[jnp.clip(oseg, 0, n - 1)]
    src = starts_i[jnp.clip(oseg, 0, n - 1)] + \
        offset[jnp.clip(oseg, 0, n - 1)] + pos
    src = jnp.clip(src, 0, x.shape[0] - 1)
    out = jnp.where(ovalid.reshape((-1,) + (1,) * (x.ndim - 1)), x[src], 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", length)


@register("sequence_enumerate")
def _sequence_enumerate(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    lengths = _lod(ctx, op.input("X")[0])
    win = int(op.attr("win_size"))
    pad = op.attr("pad_value", 0)
    flat = x.reshape(-1)
    T = flat.shape[0]
    n = lengths.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, T)
    tok = jnp.arange(T, dtype=np.dtype("int32"))
    cols = []
    for j in range(win):
        idx = jnp.clip(tok + j, 0, T - 1)
        same = (tok + j) < cum[jnp.clip(seg, 0, n - 1)]
        cols.append(jnp.where(same & valid, flat[idx],
                              jnp.asarray(pad, flat.dtype)))
    out = jnp.stack(cols, axis=1)
    ctx.set_output(op, "Out", out)
    _set_lod(ctx, op, "Out", lengths)


@register("sequence_scatter")
def _sequence_scatter(ctx, op):
    """x dense [n, cols]; per-sequence (ids, updates) tokens scattered into
    row seg(i) at column ids[i] (reference sequence_scatter_op.cc)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    ids = ctx.get_input(op, "Ids")
    upd = ctx.get_input(op, "Updates")
    lengths = _lod(ctx, op.input("Ids")[0])
    n = lengths.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, ids.reshape(-1).shape[0])
    row = jnp.where(valid, jnp.clip(seg, 0, n - 1), x.shape[0])
    col = jnp.clip(ids.reshape(-1).astype(np.dtype("int32")), 0,
                   x.shape[1] - 1)
    out = x.at[row, col].add(
        jnp.where(valid, upd.reshape(-1), 0), mode="drop")
    ctx.set_output(op, "Out", out.astype(x.dtype))


@register("sequence_conv")
def _sequence_conv(ctx, op):
    """Context-window conv over tokens, windows clipped at sequence
    boundaries (reference sequence_conv_op + math/context_project.h)."""
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "Filter")
    lengths = _lod(ctx, op.input("X")[0])
    n = lengths.shape[0]
    start = int(op.attr("contextStart", op.attr("context_start", 0)))
    clen = int(op.attr("contextLength", op.attr("context_length", 3)))
    T, D = x.shape[0], int(np.prod(x.shape[1:]))
    x2 = x.reshape(T, D)
    seg, starts, cum, valid = _seg_info(lengths, T)
    tok = jnp.arange(T, dtype=np.dtype("int32"))
    s0 = starts[jnp.clip(seg, 0, n - 1)]
    s1 = cum[jnp.clip(seg, 0, n - 1)]
    cols = []
    for j in range(clen):
        idx = tok + start + j
        inb = (idx >= s0) & (idx < s1) & valid
        idxc = jnp.clip(idx, 0, T - 1)
        cols.append(jnp.where(inb[:, None], x2[idxc], 0))
    im2col = jnp.concatenate(cols, axis=1)  # [T, clen*D]
    out = im2col @ w.reshape(clen * D, -1)
    out = jnp.where(valid[:, None], out, 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", lengths)


@register("sequence_erase")
def _sequence_erase(ctx, op):
    """Remove tokens matching any of attr 'tokens'. Bounded-LoD: the output
    keeps the physical bound; surviving tokens are front-packed per
    sequence and lengths shrink (reference sequence_erase_op.cc)."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    lengths = _lod(ctx, op.input("X")[0])
    tokens = list(op.attr("tokens", []))
    flat = x.reshape(-1)
    T = flat.shape[0]
    n = lengths.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, T)
    keep = valid
    for t in tokens:
        keep = keep & (flat != t)
    segc = jnp.clip(seg, 0, n - 1)
    new_len = jax.ops.segment_sum(
        keep.astype(np.dtype("int32")), seg, num_segments=n)
    ncum = jnp.cumsum(new_len)
    nstarts = jnp.concatenate([jnp.zeros((1,), np.dtype("int32")),
                               ncum[:-1]]).astype(np.dtype("int32"))
    # rank of each kept token within its sequence
    keep_i = keep.astype(np.dtype("int32"))
    cums = jnp.cumsum(keep_i)
    seq_prior = jnp.where(starts[segc] > 0, cums[jnp.clip(
        starts[segc] - 1, 0, T - 1)], 0)
    rank = cums - 1 - seq_prior
    dst = jnp.where(keep, nstarts[segc] + rank, T)
    out = jnp.zeros((T,), flat.dtype).at[dst].set(
        jnp.where(keep, flat, 0), mode="drop")
    ctx.set_output(op, "Out", out.reshape((-1,) + tuple(x.shape[1:])))
    _set_lod(ctx, op, "Out", new_len.astype(np.dtype("int32")))


@register("im2sequence")
def _im2sequence(ctx, op):
    """Image [N,C,H,W] -> token rows of flattened kernel patches, one
    sequence of Ho*Wo tokens per image (reference im2sequence_op.cc)."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    ksizes = [int(k) for k in op.attr("kernels")]
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    pads = [int(p) for p in op.attr("paddings", [0, 0, 0, 0])]
    patches = jax.lax.conv_general_dilated_patches(
        x, tuple(ksizes), tuple(strides),
        ((pads[0], pads[2]), (pads[1], pads[3])))
    n, ckk, oh, ow = patches.shape
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, ckk)
    ctx.set_output(op, "Out", out)
    _set_lod(ctx, op, "Out", jnp.full((n,), oh * ow, np.dtype("int32")))


@register("row_conv")
def _row_conv(ctx, op):
    """Lookahead row convolution (DeepSpeech2) — LoD path: token rows with
    windows clipped at sequence ends (reference row_conv_op.cc); dense
    fallback for [B, T, D] batched inputs without an @LOD binding."""
    import jax.numpy as jnp

    from ..lod import lod_name

    x = ctx.get_input(op, "X")
    w = ctx.get_input(op, "Filter")  # [future_context, D]
    k = w.shape[0]
    if lod_name(op.input("X")[0]) not in ctx.env:
        t = x.shape[-2]
        out = jnp.zeros_like(x)
        for j in range(k):
            shifted = jnp.pad(
                x, [(0, 0)] * (x.ndim - 2) + [(0, j), (0, 0)])[..., j:j + t, :]
            out = out + shifted * w[j]
        ctx.set_output(op, "Out", out)
        return
    lengths = _lod(ctx, op.input("X")[0])
    n = lengths.shape[0]
    T = x.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, T)
    tok = jnp.arange(T, dtype=np.dtype("int32"))
    s1 = cum[jnp.clip(seg, 0, n - 1)]
    out = jnp.zeros_like(x)
    for j in range(k):
        idx = tok + j
        inb = (idx < s1) & valid
        idxc = jnp.clip(idx, 0, T - 1)
        out = out + jnp.where(inb[:, None], x[idxc] * w[j][None, :], 0)
    ctx.set_output(op, "Out", out.astype(x.dtype))
    _set_lod(ctx, op, "Out", lengths)
