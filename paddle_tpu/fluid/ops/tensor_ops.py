"""Shape/layout manipulation ops.

Parity: reference ``operators/reshape_op.cc``, ``transpose_op.cc``,
``concat_op.cc``, ``split_op.cc``, ``slice_op.cc``, ``strided_slice_op.cc``,
``cast_op.cc``, ``stack_op.cc``, ``squeeze/unsqueeze``, ``gather/scatter``,
``expand_op.cc``, ``one_hot_op.cc``, ``shape_op.cc``, ``assign_op.cc``,
``where_op.cc``, ``pad_op.cc``, ``flatten_op.cc``, ``unstack``, ``reverse``,
``tile/expand_as``, ``lookup_table_op.cc`` (dense path).
"""

import numpy as np

from ..registry import register


def _resolve_reshape(x, shape):
    shape = list(int(s) for s in shape)
    out = []
    for i, s in enumerate(shape):
        if s == 0:  # fluid: 0 means copy input dim
            out.append(x.shape[i])
        else:
            out.append(s)
    return out


@register("reshape2")
@register("reshape")
def _reshape(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    shape = op.attr("shape")
    out = jnp.reshape(x, _resolve_reshape(x, shape))
    ctx.set_output(op, "Out", out)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register("transpose2")
@register("transpose")
def _transpose(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis")
    out = jnp.transpose(x, axis)
    ctx.set_output(op, "Out", out)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register("concat")
def _concat(ctx, op):
    import jax.numpy as jnp

    xs = ctx.get_inputs(op, "X")
    ctx.set_output(op, "Out", jnp.concatenate(xs, axis=op.attr("axis", 0)))


@register("split")
def _split(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 0)
    num = op.attr("num", 0)
    sections = op.attr("sections")
    if sections:
        idx = np.cumsum(sections[:-1])
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    for name, o in zip(op.output("Out"), outs):
        ctx.set(name, o)


@register("slice")
def _slice(ctx, op):
    x = ctx.get_input(op, "Input")
    axes = op.attr("axes")
    starts = op.attr("starts")
    ends = op.attr("ends")
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    ctx.set_output(op, "Out", x[tuple(idx)])


@register("strided_slice")
def _strided_slice(ctx, op):
    x = ctx.get_input(op, "Input")
    axes = op.attr("axes")
    starts, ends, strides = op.attr("starts"), op.attr("ends"), op.attr("strides")
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sd)
    ctx.set_output(op, "Out", x[tuple(idx)])


@register("cast")
def _cast(ctx, op):
    from ..framework import convert_dtype

    x = ctx.get_input(op, "X")
    dtype = convert_dtype(op.attr("out_dtype", op.attr("dtype", "float32")))
    ctx.set_output(op, "Out", x.astype(dtype))


@register("stack")
def _stack(ctx, op):
    import jax.numpy as jnp

    xs = ctx.get_inputs(op, "X")
    ctx.set_output(op, "Y", jnp.stack(xs, axis=op.attr("axis", 0)))


@register("unstack")
def _unstack(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 0)
    outs = [jnp.squeeze(s, axis=axis) for s in jnp.split(x, x.shape[axis], axis=axis)]
    for name, o in zip(op.output("Y"), outs):
        ctx.set(name, o)


@register("squeeze2")
@register("squeeze")
def _squeeze(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axes = op.attr("axes") or None
    if axes:
        axes = tuple(a if a >= 0 else a + x.ndim for a in axes)
        axes = tuple(a for a in axes if x.shape[a] == 1)
    out = jnp.squeeze(x, axis=axes)
    ctx.set_output(op, "Out", out)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register("unsqueeze2")
@register("unsqueeze")
def _unsqueeze(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axes = op.attr("axes")
    out = x
    for a in sorted(axes):
        out = jnp.expand_dims(out, a)
    ctx.set_output(op, "Out", out)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register("flatten2")
@register("flatten")
def _flatten(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axis = op.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    out = jnp.reshape(x, (lead, -1))
    ctx.set_output(op, "Out", out)
    if op.output("XShape"):
        ctx.set_output(op, "XShape", jnp.zeros((0,) + x.shape, dtype=x.dtype))


@register("gather")
def _gather(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    idx = ctx.get_input(op, "Index")
    ctx.set_output(op, "Out", jnp.take(x, idx.astype(np.dtype("int32")), axis=0))


@register("gather_nd")
def _gather_nd(ctx, op):
    x = ctx.get_input(op, "X")
    idx = ctx.get_input(op, "Index")
    import jax.numpy as jnp

    idx_t = tuple(jnp.moveaxis(idx, -1, 0).astype(np.dtype("int32")))
    ctx.set_output(op, "Out", x[idx_t])


@register("scatter")
def _scatter(ctx, op):
    x = ctx.get_input(op, "X")
    ids = ctx.get_input(op, "Ids")
    upd = ctx.get_input(op, "Updates")
    overwrite = op.attr("overwrite", True)
    ids = ids.astype(np.dtype("int32"))
    if overwrite:
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].add(upd)
    ctx.set_output(op, "Out", out)


@register("scatter_nd_add")
def _scatter_nd_add(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    idx = ctx.get_input(op, "Index")
    upd = ctx.get_input(op, "Updates")
    idx_t = tuple(jnp.moveaxis(idx, -1, 0).astype(np.dtype("int32")))
    ctx.set_output(op, "Out", x.at[idx_t].add(upd))


@register("expand")
def _expand(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    times = op.attr("expand_times")
    ctx.set_output(op, "Out", jnp.tile(x, times))


@register("expand_as")
def _expand_as(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "target_tensor")
    if y is None:
        y = ctx.get_input(op, "Y")
    times = [t // s for t, s in zip(y.shape, x.shape)]
    ctx.set_output(op, "Out", jnp.tile(x, times))


@register("one_hot")
def _one_hot(ctx, op):
    import jax

    x = ctx.get_input(op, "X")
    depth = op.attr("depth")
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    ctx.set_output(op, "Out", jax.nn.one_hot(x, depth, dtype=np.dtype("float32")))


@register("shape")
def _shape(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")
    ctx.set_output(op, "Out", jnp.asarray(x.shape, dtype=np.dtype("int32")))


@register("assign")
def _assign(ctx, op):
    ctx.set_output(op, "Out", ctx.get_input(op, "X"))


@register("where")
def _where(ctx, op):
    import jax.numpy as jnp

    cond = ctx.get_input(op, "Condition")
    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    ctx.set_output(op, "Out", jnp.where(cond, x, y))


@register("reverse")
def _reverse(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    axes = op.attr("axis")
    out = x
    for a in axes if isinstance(axes, (list, tuple)) else [axes]:
        out = jnp.flip(out, axis=a)
    ctx.set_output(op, "Out", out)


@register("pad")
def _pad(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    paddings = op.attr("paddings")  # flat [before0, after0, before1, after1...]
    pads = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output(op, "Out", jnp.pad(x, pads, constant_values=op.attr("pad_value", 0.0)))


@register("pad2d")
def _pad2d(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")  # NCHW
    p = op.attr("paddings")  # [top, bottom, left, right]
    mode = op.attr("mode", "constant")
    pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        out = jnp.pad(x, pads, constant_values=op.attr("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pads, mode="reflect")
    else:
        out = jnp.pad(x, pads, mode="edge")
    ctx.set_output(op, "Out", out)


@register("pad_constant_like")
def _pad_constant_like(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    y = ctx.get_input(op, "Y")
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    ctx.set_output(op, "Out", jnp.pad(y, pads, constant_values=op.attr("pad_value", 0.0)))


@register("lookup_table_v2")
@register("lookup_table")
def _lookup_table(ctx, op):
    """Embedding lookup. With ``is_sparse=True`` the backward produces a
    SelectedRows gradient (rows = ids, values = cotangents) instead of a
    dense W-grad: the autodiff lowering injects an additive eps here
    (``ctx.sparse_eps``) and reads its cotangent — see ops/autodiff.py.
    Reference ``operators/lookup_table_op.cc``."""
    import jax.numpy as jnp

    w = ctx.get_input(op, "W")
    ids = ctx.get_input(op, "Ids")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    padding_idx = op.attr("padding_idx", -1)
    out = jnp.take(w, ids.astype(np.dtype("int32")), axis=0)
    eps_map = getattr(ctx, "sparse_eps", None)
    if eps_map is not None:
        eps = eps_map.get(op.output("Out")[0])
        if eps is not None:
            # before the padding mask, so padding positions get zero
            # cotangent exactly like the dense grad path
            out = out + eps
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    ctx.set_output(op, "Out", out)


@register("zeros_like")
def _zeros_like(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.zeros_like(ctx.get_input(op, "X")))


@register("ones_like")
def _ones_like(ctx, op):
    import jax.numpy as jnp

    ctx.set_output(op, "Out", jnp.ones_like(ctx.get_input(op, "X")))


@register("increment")
def _increment(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    step = jnp.asarray(op.attr("step", 1.0)).astype(x.dtype)
    ctx.set_output(op, "Out", x + step)


@register("share_data")
def _share_data(ctx, op):
    ctx.set_output(op, "Out", ctx.get_input(op, "X"))


@register("label_smooth")
def _label_smooth(ctx, op):
    x = ctx.get_input(op, "X")
    eps = op.attr("epsilon", 0.1)
    k = x.shape[-1]
    ctx.set_output(op, "Out", x * (1.0 - eps) + eps / k)


@register("unfold")
def _unfold(ctx, op):
    import jax

    x = ctx.get_input(op, "X")  # NCHW
    ksizes = op.attr("kernel_sizes")
    strides = op.attr("strides", [1, 1])
    pads = op.attr("paddings", [0, 0, 0, 0])
    dil = op.attr("dilations", [1, 1])
    patches = jax.lax.conv_general_dilated_patches(
        x,
        filter_shape=ksizes,
        window_strides=strides,
        padding=((pads[0], pads[2] if len(pads) > 2 else pads[0]),
                 (pads[1], pads[3] if len(pads) > 3 else pads[1])),
        rhs_dilation=dil,
    )
    n, ckk, oh, ow = patches.shape
    ctx.set_output(op, "Out", patches.reshape(n, ckk, oh * ow))


@register("space_to_depth")
def _space_to_depth(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    bs = op.attr("blocksize")
    n, c, h, w = x.shape
    out = jnp.reshape(x, (n, c, h // bs, bs, w // bs, bs))
    out = jnp.transpose(out, (0, 3, 5, 1, 2, 4))
    ctx.set_output(op, "Out", jnp.reshape(out, (n, c * bs * bs, h // bs, w // bs)))


@register("pixel_shuffle")
def _pixel_shuffle(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    r = op.attr("upscale_factor")
    n, c, h, w = x.shape
    out = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
    out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
    ctx.set_output(op, "Out", jnp.reshape(out, (n, c // (r * r), h * r, w * r)))


@register("shuffle_channel")
def _shuffle_channel(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    group = op.attr("group")
    n, c, h, w = x.shape
    out = jnp.reshape(x, (n, group, c // group, h, w))
    out = jnp.swapaxes(out, 1, 2)
    ctx.set_output(op, "Out", jnp.reshape(out, (n, c, h, w)))


@register("unique")
def _unique(ctx, op):
    # Dynamic-shape op: runs at trace time only for host/static data. XLA
    # requires static shapes, so we expose size-preserving unique with
    # fixed-size output (reference semantic subset).
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")
    out, idx = jnp.unique(x, return_inverse=True, size=x.shape[0])
    ctx.set_output(op, "Out", out)
    ctx.set_output(op, "Index", idx.astype(np.dtype("int32")))


@register("merge_selected_rows")
def _merge_selected_rows(ctx, op):
    """Sum duplicate rows of a SelectedRows pair (reference
    ``operators/merge_selected_rows_op.cc`` / math/selected_rows_functor).
    Static-shape formulation: output keeps the same rows array; the FIRST
    occurrence of each row id carries the full sum, later duplicates zero."""
    import jax.numpy as jnp

    xname = op.input("X")[0]
    rows = ctx.get(xname + "@ROWS")
    vals = ctx.get(xname)
    n = rows.shape[0]
    # first-occurrence index for each position's row id
    eq = rows[None, :] == rows[:, None]                  # [n, n]
    first_idx = jnp.argmax(eq, axis=1)                   # min j with same id
    is_first = first_idx == jnp.arange(n)
    # summed value per row id, scattered to every occurrence then masked
    summed = jnp.zeros_like(vals).at[first_idx].add(vals)
    merged = jnp.where(is_first[:, None], summed, jnp.zeros_like(vals))
    out = op.output("Out")[0]
    ctx.set(out, merged)
    ctx.set(out + "@ROWS", rows)


@register("get_tensor_from_selected_rows")
def _get_tensor_from_selected_rows(ctx, op):
    """Densify a SelectedRows var into its full-height tensor (reference
    ``operators/get_tensor_from_selected_rows_op.cc``)."""
    import jax.numpy as jnp

    xname = op.input("X")[0]
    rows = ctx.get(xname + "@ROWS")
    vals = ctx.get(xname)
    xvar = ctx.var(xname)
    height = op.attr("height", None)
    if height is None:
        # the var records (-1, dim...) — callers must pass height for the
        # dense shape; fall back to max row + 1 is dynamic, so require it
        raise ValueError("get_tensor_from_selected_rows needs a 'height' attr")
    dense = jnp.zeros((int(height),) + tuple(vals.shape[1:]), vals.dtype)
    ctx.set_output(op, "Out", dense.at[rows].add(vals))
