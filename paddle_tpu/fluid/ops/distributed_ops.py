"""Distributed (parameter-server tier) ops.

Parity: reference ``operators/distributed_ops/distributed_lookup_table_op.cc``
and the pslib pull/push path (``framework/fleet/fleet_wrapper.h:77,103``).
TPU-native: the table lives in host RAM (``paddle_tpu/distributed/ps.py`` —
native C++ shard store); the device graph pulls rows with
``jax.pure_callback`` (XLA host callback, overlapped by the runtime) instead
of an RPC per step. The gradient push is an explicit ``distributed_push``
op appended by ``append_backward`` AFTER the autodiff op — the payload is an
env binding (out_name + '@PS_GRAD'/'@PS_ROWS') produced by the autodiff
lowering, so AMP can divide out its loss scale and zero the payload on
overflow (attrs ``scale``/``scale_var``/``gate_var``) before the ordered
``io_callback`` hands it to the host-side table optimizer — the async-PS
update model.
"""

import numpy as np

from ..registry import register


def _pull_fn(table_name):
    def pull(ids):
        from ...distributed import ps

        return ps.get_table(table_name).pull(np.asarray(ids))

    return pull


@register("distributed_lookup_table")
def _distributed_lookup_table(ctx, op):
    import jax
    import jax.numpy as jnp

    ids = ctx.get_input(op, "Ids")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    table_name = op.attr("table_name")
    dim = int(op.attr("dim"))
    # int32 on device; the host pull widens to int64 (table.pull)
    flat = jnp.reshape(ids, (-1,)).astype(np.dtype("int32"))
    out = jax.pure_callback(
        _pull_fn(table_name),
        jax.ShapeDtypeStruct((flat.shape[0], dim), np.dtype("float32")),
        flat,
        vmap_method="sequential",
    )
    out = jnp.reshape(out, tuple(ids.shape) + (dim,))
    # autodiff injects an additive eps whose cotangent IS the push payload;
    # it goes BEFORE the padding mask so padded positions get zero cotangent
    eps_map = getattr(ctx, "sparse_eps", None)
    if eps_map is not None:
        eps = eps_map.get(op.output("Out")[0])
        if eps is not None:
            out = out + eps
    padding_idx = op.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    dtype = op.attr("dtype", "float32")
    if str(dtype) != "float32":
        out = out.astype(np.dtype(dtype))
    ctx.set_output(op, "Out", out)


@register("distributed_push")
def _distributed_push(ctx, op):
    """Ship the SelectedRows cotangent to the host table optimizer.

    Ordered io_callback: an effect, never DCE'd, sequenced with other host
    effects. AMP seam: ``scale``/``scale_var`` divide the payload (undoing
    the loss scale baked into the cotangent) and ``gate_var`` multiplies it
    (0.0 on overflow — pushing zeros is a no-op update for sgd/adagrad,
    mirroring the zero-grad device step AMP takes on overflow)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    values = ctx.get_input(op, "Values")
    rows = ctx.get_input(op, "Rows")
    scale = float(op.attr("scale", 1.0))
    if scale != 1.0:
        values = values / scale
    scale_var = op.attr("scale_var", None)
    if scale_var is not None:
        values = values / jnp.reshape(
            jax.lax.stop_gradient(ctx.get(scale_var)), ()).astype("float32")
    gate_var = op.attr("gate_var", None)
    if gate_var is not None:
        # select, not multiply: inf * 0 == nan would still reach the table
        gate = jnp.reshape(jax.lax.stop_gradient(ctx.get(gate_var)), ())
        values = jnp.where(gate > 0, values, jnp.zeros_like(values))
    tname = op.attr("table_name")
    lr = float(op.attr("lr", 0.01))
    optname = op.attr("optimizer", "sgd")

    def _push(r, v, _t=tname, _lr=lr, _o=optname):
        from ...distributed import ps

        ps.get_table(_t).push(np.asarray(r), np.asarray(v),
                              lr=_lr, optimizer=_o)
        return np.int32(0)

    io_callback(_push, jax.ShapeDtypeStruct((), np.dtype("int32")),
                rows, values, ordered=True)


@register("distributed_table_init")
def _distributed_table_init(ctx, op):
    """(Re-)initialize a host table — placed in the STARTUP program by
    ``layers.embedding(is_distributed=True)`` so ``exe.run(startup)`` resets
    the host store exactly like it resets device parameters."""
    import jax
    from jax.experimental import io_callback

    tname = op.attr("table_name")

    def _init(_t=tname):
        from ...distributed import ps

        ps.get_table(_t).reinit()
        return np.int32(0)

    io_callback(_init, jax.ShapeDtypeStruct((), np.dtype("int32")),
                ordered=True)
