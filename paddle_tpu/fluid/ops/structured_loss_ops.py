"""Structured / sampled loss op lowerings — reference
``linear_chain_crf_op.cc``, ``crf_decoding_op.cc``, ``warpctc_op.cc``,
``ctc_align_op`` (greedy decode), ``edit_distance_op.cc``, ``nce_op.cc``,
``hierarchical_sigmoid_op.cc``, ``sample_logits`` (sampled softmax).

TPU-native notes:
* CRF forward/Viterbi run in LOG space as one ``lax.scan`` over the padded
  pack of bounded-LoD emissions (the reference works in exp space with
  per-step renormalization on the CPU); gradients come from ``jax.grad``
  through the scan — the reference's hand-written CRF backward is deleted.
* warpctc maps to ``optax.ctc_loss`` (the public JAX CTC) over the padded
  pack; no external warp-ctc library.
* NCE / sampled softmax draw their negatives from the threaded PRNG
  (``ctx.next_rng``) so autodiff replay sees identical samples.
* hsigmoid uses the reference's complete-binary-tree heap code
  (MatrixBitCodeFunctor semantics: leaf code = label + num_classes, path =
  binary prefixes) with masked fixed-bound paths.
"""

import numpy as np

from ..registry import register
from .sequence_ops import _lod, _seg_info
from .rnn_ops import _pack


@register("linear_chain_crf")
def _linear_chain_crf(ctx, op):
    import jax
    import jax.numpy as jnp

    em = ctx.get_input(op, "Emission")       # [total, K]
    trans = ctx.get_input(op, "Transition")  # [K+2, K] (rows 0,1 start/end)
    label = ctx.get_input(op, "Label")       # [total, 1]
    lengths = _lod(ctx, op.input("Emission")[0])
    n = lengths.shape[0]
    K = em.shape[1]
    start_w, end_w, T = trans[0], trans[1], trans[2:]  # T[from, to]

    epad, mask = _pack(em, lengths)                     # [n, Tb, K]
    lpad, _ = _pack(label.reshape(-1, 1).astype(np.dtype("int32")), lengths)
    lpad = lpad[..., 0]                                 # [n, Tb]
    Tb = epad.shape[1]

    # log-partition via forward algorithm
    alpha0 = start_w[None, :] + epad[:, 0]              # [n, K]

    def fwd(alpha, x):
        e_t, m_t = x                                    # [n, K], [n]
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + T[None, :, :], axis=1) + e_t
        keep = m_t[:, None]
        return jnp.where(keep, nxt, alpha), None

    alphaT, _ = jax.lax.scan(
        fwd, alpha0, (epad[:, 1:].transpose(1, 0, 2), mask[:, 1:].T))
    logZ = jax.scipy.special.logsumexp(alphaT + end_w[None, :], axis=1)

    # score of the gold path
    rows = jnp.arange(n)
    first_lab = lpad[:, 0]
    gold = start_w[first_lab] + epad[:, 0][rows, first_lab]

    def gold_step(carry, x):
        score, prev_lab = carry
        e_t, l_t, m_t = x
        step = T[prev_lab, l_t] + e_t[rows, l_t]
        score = jnp.where(m_t, score + step, score)
        prev_lab = jnp.where(m_t, l_t, prev_lab)
        return (score, prev_lab), None

    (gold, last_lab), _ = jax.lax.scan(
        gold_step, (gold, first_lab),
        (epad[:, 1:].transpose(1, 0, 2), lpad[:, 1:].T, mask[:, 1:].T))
    gold = gold + end_w[last_lab]

    ll = (gold - logZ)[:, None]                          # [n, 1]
    ctx.set_output(op, "LogLikelihood", ll.astype(em.dtype))
    # aux outputs for API parity (alpha in log space)
    ctx.set_output(op, "Alpha", alphaT.astype(em.dtype))
    ctx.set_output(op, "EmissionExps", jnp.exp(em))
    ctx.set_output(op, "TransitionExps", jnp.exp(trans))


@register("crf_decoding")
def _crf_decoding(ctx, op):
    import jax
    import jax.numpy as jnp

    em = ctx.get_input(op, "Emission")
    trans = ctx.get_input(op, "Transition")
    lengths = _lod(ctx, op.input("Emission")[0])
    n = lengths.shape[0]
    K = em.shape[1]
    total = em.shape[0]
    start_w, end_w, T = trans[0], trans[1], trans[2:]
    epad, mask = _pack(em, lengths)
    Tb = epad.shape[1]

    delta0 = start_w[None, :] + epad[:, 0]

    def vit(delta, x):
        e_t, m_t = x
        cand = delta[:, :, None] + T[None, :, :]        # [n, from, to]
        best = jnp.max(cand, axis=1) + e_t
        arg = jnp.argmax(cand, axis=1).astype(np.dtype("int32"))
        keep = m_t[:, None]
        return jnp.where(keep, best, delta), \
            jnp.where(keep, arg, -1)

    deltaT, backp = jax.lax.scan(
        vit, delta0, (epad[:, 1:].transpose(1, 0, 2), mask[:, 1:].T))
    # backp: [Tb-1, n, K]; add end weights, backtrack
    rows = jnp.arange(n)
    last = jnp.argmax(deltaT + end_w[None, :], axis=1).astype(
        np.dtype("int32"))

    def back(lab, bp_t):
        prev = bp_t[rows, lab]
        lab2 = jnp.where(prev >= 0, prev, lab)
        return lab2, lab

    _, path_rev = jax.lax.scan(back, last, backp[::-1])
    # path_rev[t] is the label at time (Tb-1-t); prepend first label
    first = _  # final carry = label at t=0
    path = jnp.concatenate([first[None, :], path_rev[::-1]], axis=0)  # [Tb,n]
    path = path.T                                        # [n, Tb]
    # flatten back to token rows
    seg, starts, cum, valid = _seg_info(lengths, total)
    tok = jnp.arange(total, dtype=np.dtype("int32"))
    pos = tok - starts[jnp.clip(seg, 0, n - 1)]
    flat = path[jnp.clip(seg, 0, n - 1), jnp.clip(pos, 0, Tb - 1)]
    flat = jnp.where(valid, flat, 0)[:, None].astype(np.dtype("int64"))
    ctx.set_output(op, "ViterbiPath", flat)
    from ..lod import lod_name

    names = op.output("ViterbiPath")
    if names:
        ctx.env[lod_name(names[0])] = lengths


@register("warpctc")
def _warpctc(ctx, op):
    import jax.numpy as jnp
    import optax

    logits = ctx.get_input(op, "Logits")
    label = ctx.get_input(op, "Label")
    blank = int(op.attr("blank", 0))
    norm_by_times = bool(op.attr("norm_by_times", False))
    if op.attr("padded", False):
        # padded-tensor API: Logits [B, T, V], Label [B, N] + lengths
        import jax.numpy as jnp2

        llen = ctx.get_input(op, "LogitsLength").reshape(-1).astype(
            np.dtype("int32"))
        tlen = ctx.get_input(op, "LabelLength").reshape(-1).astype(
            np.dtype("int32"))
        lpad = logits
        ypad = label.reshape(label.shape[0], -1).astype(np.dtype("int32"))
        lmask = jnp2.arange(lpad.shape[1])[None, :] < llen[:, None]
        ymask = jnp2.arange(ypad.shape[1])[None, :] < tlen[:, None]
    else:
        llen = _lod(ctx, op.input("Logits")[0])
        tlen = _lod(ctx, op.input("Label")[0])
        lpad, lmask = _pack(logits, llen)              # [n, Tb, K+1]
        ypad, ymask = _pack(label.reshape(-1, 1).astype(np.dtype("int32")),
                            tlen)
        ypad = ypad[..., 0]
    loss = optax.ctc_loss(
        lpad, (~lmask).astype(lpad.dtype),
        ypad, (~ymask).astype(lpad.dtype), blank_id=blank)  # [n]
    if norm_by_times:
        loss = loss / jnp.maximum(llen, 1).astype(loss.dtype)
    ctx.set_output(op, "Loss", loss[:, None])
    ctx.set_output(op, "WarpCTCGrad", jnp.zeros_like(lpad))  # parity slot


@register("ctc_align")
def _ctc_align(ctx, op):
    """Greedy CTC decode: collapse repeats then drop blanks, front-packed
    bounded-LoD output (reference ctc_align_op.cu)."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")  # [total, 1] argmaxed ids (LoD)
    blank = int(op.attr("blank", 0))
    lengths = _lod(ctx, op.input("Input")[0])
    n = lengths.shape[0]
    flat = x.reshape(-1).astype(np.dtype("int32"))
    total = flat.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, total)
    tok = jnp.arange(total, dtype=np.dtype("int32"))
    pos = tok - starts[jnp.clip(seg, 0, n - 1)]
    prev = jnp.where(pos > 0, flat[jnp.clip(tok - 1, 0, total - 1)], -1)
    keep = valid & (flat != blank) & (flat != prev)
    # front-pack kept tokens per sequence (same scheme as sequence_erase)
    keep_i = keep.astype(np.dtype("int32"))
    new_len = jax.ops.segment_sum(keep_i, seg, num_segments=n)
    ncum = jnp.cumsum(new_len)
    nstarts = jnp.concatenate(
        [jnp.zeros((1,), np.dtype("int32")), ncum[:-1]]).astype(
        np.dtype("int32"))
    cums = jnp.cumsum(keep_i)
    segc = jnp.clip(seg, 0, n - 1)
    seq_prior = jnp.where(starts[segc] > 0,
                          cums[jnp.clip(starts[segc] - 1, 0, total - 1)], 0)
    rank = cums - 1 - seq_prior
    dst = jnp.where(keep, nstarts[segc] + rank, total)
    out = jnp.zeros((total,), np.dtype("int64")).at[dst].set(
        jnp.where(keep, flat, 0).astype(np.dtype("int64")), mode="drop")
    ctx.set_output(op, "Output", out[:, None])
    from ..lod import lod_name

    names = op.output("Output")
    if names:
        ctx.env[lod_name(names[0])] = new_len.astype(np.dtype("int32"))


@register("edit_distance")
def _edit_distance(ctx, op):
    """Levenshtein distance per sequence pair (reference
    edit_distance_op.cc) — DP rows as a lax.scan carry, masked to each
    pair's true lengths."""
    import jax
    import jax.numpy as jnp

    hyp = ctx.get_input(op, "Hyps")
    ref = ctx.get_input(op, "Refs")
    normalized = bool(op.attr("normalized", False))
    if op.attr("padded", False):
        # padded-tensor API: Hyps [B, Lh], Refs [B, Lr] + lengths
        hlen = ctx.get_input(op, "HypsLength").reshape(-1).astype(
            np.dtype("int32"))
        rlen = ctx.get_input(op, "RefsLength").reshape(-1).astype(
            np.dtype("int32"))
        n = hlen.shape[0]
        hpad = hyp.reshape(n, -1).astype(np.dtype("int32"))
        rpad = ref.reshape(n, -1).astype(np.dtype("int32"))
    else:
        hlen = _lod(ctx, op.input("Hyps")[0])
        rlen = _lod(ctx, op.input("Refs")[0])
        n = hlen.shape[0]
        hpad, _hm = _pack(hyp.reshape(-1, 1).astype(np.dtype("int32")),
                          hlen)
        rpad, _rm = _pack(ref.reshape(-1, 1).astype(np.dtype("int32")),
                          rlen)
        hpad, rpad = hpad[..., 0], rpad[..., 0]   # [n, Hb], [n, Rb]
    Hb, Rb = hpad.shape[1], rpad.shape[1]
    BIG = np.float32(1e9)

    # dp[j] over ref prefix j; scan over hyp tokens
    init = jnp.broadcast_to(
        jnp.arange(Rb + 1, dtype=np.dtype("float32"))[None, :],
        (n, Rb + 1))
    # positions beyond rlen clamp later; run full DP then read [hlen, rlen]
    jidx = jnp.arange(1, Rb + 1)

    def row(dp, x):
        h_t, i = x                       # [n], scalar index (1-based)
        sub = (rpad != h_t[:, None]).astype(np.dtype("float32"))
        # dp_new[0] = i
        def inner(carry, jx):
            left = carry                 # dp_new[j-1]
            j, diag, up, s = jx
            val = jnp.minimum(jnp.minimum(up + 1, left + 1), diag + s)
            return val, val

        diag = dp[:, :-1]                # dp[j-1]
        up = dp[:, 1:]                   # dp[j]
        first = jnp.full((n,), i, np.dtype("float32"))
        _, cols = jax.lax.scan(
            inner, first,
            (jidx, diag.T, up.T, sub.T))
        dp_new = jnp.concatenate([first[:, None], cols.T], axis=1)
        return dp_new, dp_new

    hidx = jnp.arange(1, Hb + 1).astype(np.dtype("float32"))
    _, rows = jax.lax.scan(row, init, (hpad.T, hidx))
    # rows: [Hb, n, Rb+1]; distance = dp[hlen][rlen] (hlen=0 -> init row)
    all_rows = jnp.concatenate([init[None], rows], axis=0)  # [Hb+1, n, Rb+1]
    d = all_rows[jnp.clip(hlen, 0, Hb), jnp.arange(n),
                 jnp.clip(rlen, 0, Rb)]
    if normalized:
        d = d / jnp.maximum(rlen, 1).astype(d.dtype)
    ctx.set_output(op, "Out", d[:, None].astype(np.dtype("float32")))
    ctx.set_output(op, "SequenceNum", jnp.asarray(n, np.dtype("int32")))


@register("nce", has_state=True)
def _nce(ctx, op):
    """Noise-contrastive estimation (reference nce_op.cc) with uniform
    negative sampling from the threaded PRNG."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")       # [B, D]
    label = ctx.get_input(op, "Label").reshape(-1)  # [B]
    w = ctx.get_input(op, "Weight")      # [C, D]
    b = ctx.get_input(op, "Bias")        # [C]
    S = int(op.attr("num_neg_samples", 10))
    C = int(op.attr("num_total_classes"))
    B = x.shape[0]
    key = ctx.next_rng()
    neg = jax.random.randint(key, (B, S), 0, C)         # [B, S]
    lab = label.astype(np.dtype("int32"))
    pos_logit = jnp.sum(x * w[lab], axis=1)
    if b is not None:
        pos_logit = pos_logit + b.reshape(-1)[lab]
    neg_logit = jnp.einsum("bd,bsd->bs", x, w[neg])
    if b is not None:
        neg_logit = neg_logit + b.reshape(-1)[neg]
    # NCE with uniform noise: P_n = 1/C
    logq = jnp.log(jnp.asarray(S / C, x.dtype))
    pos_p = jax.nn.log_sigmoid(pos_logit - logq)
    neg_p = jax.nn.log_sigmoid(-(neg_logit - logq))
    cost = -(pos_p + jnp.sum(neg_p, axis=1))
    ctx.set_output(op, "Cost", cost[:, None])
    ctx.set_output(op, "SampleLogits", neg_logit)
    ctx.set_output(op, "SampleLabels", neg.astype(np.dtype("int64")))


@register("hierarchical_sigmoid")
def _hierarchical_sigmoid(ctx, op):
    """Complete-binary-tree hierarchical softmax (reference
    hierarchical_sigmoid_op.cc + MatrixBitCodeFunctor): leaf code =
    label + num_classes; path nodes are the code's binary prefixes
    (heap indices), sign of each step = the following bit."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "X")           # [B, D]
    w = ctx.get_input(op, "W")           # [num_classes-1, D] internal nodes
    b = ctx.get_input(op, "Bias")        # [num_classes-1, 1] or None
    label = ctx.get_input(op, "Label").reshape(-1).astype(np.dtype("int32"))
    C = int(op.attr("num_classes"))
    B = x.shape[0]
    max_len = int(np.ceil(np.log2(max(C, 2)))) + 1
    code = label + C                     # heap leaf id
    # path: prefixes code >> k for k = len-1 .. 1 ; bit = (code >> (k-1)) & 1
    length = jnp.floor(jnp.log2(code.astype(np.dtype("float32")))).astype(
        np.dtype("int32"))               # number of steps
    ks = jnp.arange(max_len, dtype=np.dtype("int32"))  # step index j
    # step j uses node (code >> (length - j)) and bit (code >> (length-j-1))&1
    shift = length[:, None] - ks[None, :]
    validp = shift >= 1
    node = jnp.right_shift(code[:, None], jnp.maximum(shift, 1))
    bit = jnp.right_shift(code[:, None], jnp.maximum(shift - 1, 0)) & 1
    nidx = jnp.clip(node - 1, 0, w.shape[0] - 1)  # internal node row
    logits = jnp.einsum("bd,bkd->bk", x, w[nidx])
    if b is not None:
        logits = logits + b.reshape(-1)[nidx]
    # bit==1 -> right child: P = sigmoid(logit); bit==0 -> 1 - sigmoid
    sign = jnp.where(bit == 1, 1.0, -1.0).astype(x.dtype)
    logp = jax.nn.log_sigmoid(sign * logits)
    cost = -jnp.sum(jnp.where(validp, logp, 0.0), axis=1)
    ctx.set_output(op, "Out", cost[:, None])
    ctx.set_output(op, "PreOut", logits)


@register("sampled_softmax_with_cross_entropy", has_state=True)
@register("sample_logits", has_state=True)
def _sampled_softmax(ctx, op):
    """Sampled-softmax CE (reference sample_logits_op.cc + Python wrapper):
    softmax over {true, S uniform samples} with logQ correction."""
    import jax
    import jax.numpy as jnp

    logits = ctx.get_input(op, "Logits")   # [B, C]
    label = ctx.get_input(op, "Label").reshape(-1).astype(np.dtype("int32"))
    S = int(op.attr("num_samples", 5))
    B, C = logits.shape
    key = ctx.next_rng()
    neg = jax.random.randint(key, (B, S), 0, C)
    rows = jnp.arange(B)
    true_logit = logits[rows, label][:, None]
    neg_logit = jnp.take_along_axis(logits, neg, axis=1)
    # logQ correction (uniform proposal): q = S/C
    logq = jnp.log(jnp.asarray(S / C, logits.dtype))
    # mask accidental hits of the true class among samples
    hit = (neg == label[:, None])
    cat = jnp.concatenate(
        [true_logit,
         jnp.where(hit, -1e30, neg_logit - logq)], axis=1)
    loss = -jax.nn.log_softmax(cat, axis=1)[:, 0]
    ctx.set_output(op, "Loss", loss[:, None])
    ctx.set_output(op, "Samples", neg.astype(np.dtype("int64")))