"""Optimizer update ops.

Parity: reference ``operators/optimizers/`` — sgd, momentum (+nesterov,
+lars), adam/adamax/adagrad/decayed_adagrad/adadelta, rmsprop, ftrl, lamb,
dpsgd. Updates write the param (persistable) in the functional env; the
executor commits them with buffer donation so an update is in-place at the
XLA level, like the reference's in-place scope mutation.
"""

import numpy as np

from ..registry import register


def _lr(ctx, op):
    import jax.numpy as jnp

    lr = ctx.get_input(op, "LearningRate")
    return jnp.reshape(lr, ()).astype(ctx.get_input(op, "Param").dtype)


def _sparse_grad(ctx, op):
    """(rows, values) when the Grad input is a SelectedRows var, else None.
    TPU encoding of reference SelectedRows (selected_rows.h:32): values
    bound to the grad name, int32 rows to name+'@ROWS'; duplicate rows sum."""
    gname = op.input("Grad")[0]
    gvar = ctx.var(gname)
    if gvar is None or getattr(gvar, "type", "lod_tensor") != "selected_rows":
        return None
    return ctx.get(gname + "@ROWS"), ctx.get(gname)


def _fused_rows(p, rows, vals):
    """Fused sparse-update prep, all O(#lookups): unique touched rows (the
    lookup's dedup mirrored in the backward), the per-unique-row summed
    gradient, and a validity mask for the static-size padding.
    ``jnp.unique(size=n)`` pads with fill_value=0 / count 0; padded lanes
    are masked out downstream so no dense [vocab, ...] gradient — or any
    vocab-sized temporary at all — is ever materialized."""
    import jax.numpy as jnp

    n = rows.shape[0]
    vals = vals.astype(p.dtype).reshape((n,) + p.shape[1:])
    uniq, inv, counts = jnp.unique(rows, return_inverse=True,
                                   return_counts=True, size=n, fill_value=0)
    g = jnp.zeros_like(vals).at[inv.reshape(-1)].add(vals)
    valid = (counts > 0).reshape((n,) + (1,) * (p.ndim - 1))
    return uniq, valid, g


def _apply_rows(dst, uniq, valid, new_rows, old_rows):
    """Scatter the per-row update into ``dst`` additively (delta form):
    padded duplicate lanes (all index 0) carry a masked zero delta, so the
    scatter-add is exact without needing collision-free indices."""
    import jax.numpy as jnp

    delta = jnp.where(valid, new_rows - old_rows, 0).astype(dst.dtype)
    return dst.at[uniq].add(delta)


@register("sgd")
def _sgd(ctx, op):
    p = ctx.get_input(op, "Param")
    lr = _lr(ctx, op)
    sp = _sparse_grad(ctx, op)
    if sp is not None:
        rows, vals = sp
        # scatter-add: duplicate rows accumulate, untouched rows unchanged
        ctx.set_output(op, "ParamOut",
                       p.at[rows].add((-lr * vals).astype(p.dtype).reshape(
                           (rows.shape[0],) + p.shape[1:])))
        return
    g = ctx.get_input(op, "Grad")
    ctx.set_output(op, "ParamOut", p - lr * g)


@register("momentum")
def _momentum(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    v = ctx.get_input(op, "Velocity")
    mu = op.attr("mu")
    lr = _lr(ctx, op)
    sp = _sparse_grad(ctx, op)
    if sp is not None:
        # lazy rows-only update (reference momentum_op.h SelectedRows
        # path), fused: gather touched rows, update, scatter-add the delta
        # — O(#lookups) work, no vocab-sized gradient temporary
        rows, vals = sp
        uniq, valid, g = _fused_rows(p, rows, vals)
        p_rows, v_rows = p[uniq], v[uniq]
        v_new_rows = mu * v_rows + g
        if op.attr("use_nesterov", False):
            p_new_rows = p_rows - (g + mu * v_new_rows) * lr
        else:
            p_new_rows = p_rows - lr * v_new_rows
        ctx.set_output(op, "ParamOut",
                       _apply_rows(p, uniq, valid, p_new_rows, p_rows))
        ctx.set_output(op, "VelocityOut",
                       _apply_rows(v, uniq, valid, v_new_rows, v_rows))
        return
    g = ctx.get_input(op, "Grad")
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    ctx.set_output(op, "ParamOut", p_new)
    ctx.set_output(op, "VelocityOut", v_new)


@register("lars_momentum")
def _lars_momentum(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    v = ctx.get_input(op, "Velocity")
    mu = op.attr("mu")
    coeff = op.attr("lars_coeff", 0.001)
    decay = op.attr("lars_weight_decay", 0.0005)
    lr = _lr(ctx, op)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * p_norm / (g_norm + decay * p_norm + 1e-12)
    v_new = mu * v + local_lr * (g + decay * p)
    ctx.set_output(op, "ParamOut", p - v_new)
    ctx.set_output(op, "VelocityOut", v_new)


@register("adam")
def _adam(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    m = ctx.get_input(op, "Moment1")
    v = ctx.get_input(op, "Moment2")
    b1p = ctx.get_input(op, "Beta1Pow")
    b2p = ctx.get_input(op, "Beta2Pow")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(ctx, op)
    b1p_, b2p_ = jnp.reshape(b1p, ()), jnp.reshape(b2p, ())
    lr_t = lr * jnp.sqrt(1 - b2p_) / (1 - b1p_)
    sp = _sparse_grad(ctx, op)
    if sp is not None:
        # lazy-mode sparse adam (reference adam_op.h SelectedRows kernel):
        # moments decay and params move only on touched rows. Fused
        # gather/update/scatter-add — the moment slots are row-sparse too,
        # and nothing vocab-sized is materialized
        rows, vals = sp
        uniq, valid, g = _fused_rows(p, rows, vals)
        p_rows, m_rows, v_rows = p[uniq], m[uniq], v[uniq]
        m_new_rows = b1 * m_rows + (1 - b1) * g
        v_new_rows = b2 * v_rows + (1 - b2) * jnp.square(g)
        p_new_rows = p_rows - lr_t * m_new_rows / (jnp.sqrt(v_new_rows)
                                                   + eps)
        m_new = _apply_rows(m, uniq, valid, m_new_rows, m_rows)
        v_new = _apply_rows(v, uniq, valid, v_new_rows, v_rows)
        p_new = _apply_rows(p, uniq, valid, p_new_rows, p_rows)
    else:
        g = ctx.get_input(op, "Grad")
        m_new = b1 * m + (1 - b1) * g
        v_new = b2 * v + (1 - b2) * jnp.square(g)
        p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    ctx.set_output(op, "ParamOut", p_new)
    ctx.set_output(op, "Moment1Out", m_new)
    ctx.set_output(op, "Moment2Out", v_new)
    ctx.set_output(op, "Beta1PowOut", b1p * b1)
    ctx.set_output(op, "Beta2PowOut", b2p * b2)


@register("adamax")
def _adamax(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    m = ctx.get_input(op, "Moment")
    inf_norm = ctx.get_input(op, "InfNorm")
    b1p = ctx.get_input(op, "Beta1Pow")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(ctx, op)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    lr_t = lr / (1 - jnp.reshape(b1p, ()))
    ctx.set_output(op, "ParamOut", p - lr_t * m_new / inf_new)
    ctx.set_output(op, "MomentOut", m_new)
    ctx.set_output(op, "InfNormOut", inf_new)
    ctx.set_output(op, "Beta1PowOut", b1p * b1)


@register("adagrad")
def _adagrad(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    m = ctx.get_input(op, "Moment")
    eps = op.attr("epsilon", 1e-6)
    lr = _lr(ctx, op)
    sp = _sparse_grad(ctx, op)
    if sp is not None:
        rows, vals = sp
        uniq, valid, g = _fused_rows(p, rows, vals)
        p_rows, m_rows = p[uniq], m[uniq]
        m_new_rows = m_rows + jnp.square(g)
        p_new_rows = p_rows - lr * g / (jnp.sqrt(m_new_rows) + eps)
        ctx.set_output(op, "ParamOut",
                       _apply_rows(p, uniq, valid, p_new_rows, p_rows))
        ctx.set_output(op, "MomentOut",
                       _apply_rows(m, uniq, valid, m_new_rows, m_rows))
        return
    g = ctx.get_input(op, "Grad")
    m_new = m + jnp.square(g)
    ctx.set_output(op, "ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output(op, "MomentOut", m_new)


@register("decayed_adagrad")
def _decayed_adagrad(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    m = ctx.get_input(op, "Moment")
    decay = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    lr = _lr(ctx, op)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    ctx.set_output(op, "ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output(op, "MomentOut", m_new)


@register("adadelta")
def _adadelta(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    avg_sq_g = ctx.get_input(op, "AvgSquaredGrad")
    avg_sq_u = ctx.get_input(op, "AvgSquaredUpdate")
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    g2_new = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (g2_new + eps)) * g
    u2_new = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    ctx.set_output(op, "ParamOut", p + update)
    ctx.set_output(op, "AvgSquaredGradOut", g2_new)
    ctx.set_output(op, "AvgSquaredUpdateOut", u2_new)


@register("rmsprop")
def _rmsprop(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    ms = ctx.get_input(op, "MeanSquare")
    mom = ctx.get_input(op, "Moment")
    rho = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    momentum = op.attr("momentum", 0.0)
    centered = op.attr("centered", False)
    lr = _lr(ctx, op)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg = ctx.get_input(op, "MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        denom = jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
        ctx.set_output(op, "MeanGradOut", mg_new)
    else:
        denom = jnp.sqrt(ms_new + eps)
    mom_new = momentum * mom + lr * g / denom
    ctx.set_output(op, "ParamOut", p - mom_new)
    ctx.set_output(op, "MeanSquareOut", ms_new)
    ctx.set_output(op, "MomentOut", mom_new)


@register("ftrl")
def _ftrl(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    sq = ctx.get_input(op, "SquaredAccumulator")
    lin = ctx.get_input(op, "LinearAccumulator")
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    lr_power = op.attr("lr_power", -0.5)
    lr = _lr(ctx, op)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    ctx.set_output(op, "ParamOut", pre / denom)
    ctx.set_output(op, "SquaredAccumOut", new_sq)
    ctx.set_output(op, "LinearAccumOut", new_lin)


@register("lamb")
def _lamb(ctx, op):
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    m = ctx.get_input(op, "Moment1")
    v = ctx.get_input(op, "Moment2")
    b1p = ctx.get_input(op, "Beta1Pow")
    b2p = ctx.get_input(op, "Beta2Pow")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    lr = _lr(ctx, op)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    m_hat = m_new / (1 - jnp.reshape(b1p, ()))
    v_hat = v_new / (1 - jnp.reshape(b2p, ()))
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    ctx.set_output(op, "ParamOut", p - lr * trust * r)
    ctx.set_output(op, "Moment1Out", m_new)
    ctx.set_output(op, "Moment2Out", v_new)
    ctx.set_output(op, "Beta1PowOut", b1p * b1)
    ctx.set_output(op, "Beta2PowOut", b2p * b2)


@register("dpsgd", has_state=True)
def _dpsgd(ctx, op):
    import jax
    import jax.numpy as jnp

    p = ctx.get_input(op, "Param")
    g = ctx.get_input(op, "Grad")
    lr = _lr(ctx, op)
    clip = op.attr("clip", 10.0)
    batch_size = op.attr("batch_size", 16.0)
    sigma = op.attr("sigma", 1.0)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g_clip = g / jnp.maximum(1.0, g_norm / clip)
    noise = sigma * clip / batch_size * jax.random.normal(ctx.next_rng(), g.shape)
    ctx.set_output(op, "ParamOut", p - lr * (g_clip + noise))


@register("dgc")
def _dgc(ctx, op):
    """Deep Gradient Compression step (reference ``operators/dgc_op.cc``):
    momentum correction + error feedback + top-k masked-dense gradient.
    Computation lives in paddle_tpu/parallel/dgc.py. Pre-rampup steps pass
    the plain momentum velocity through (reference rampup semantics),
    gated in-graph on CurrentStep."""
    import jax.numpy as jnp

    from ...parallel import dgc as dgc_lib

    import jax

    u = ctx.get_input(op, "U")
    v = ctx.get_input(op, "V")
    g = ctx.get_input(op, "Grad")
    m = op.attr("m", 0.9)
    sparsity = list(op.attr("sparsity", [0.999]))
    rampup = op.attr("rampup_begin_step", 0)
    rampup_step = max(int(op.attr("rampup_step", 1)), 1)
    step_in = (jnp.reshape(ctx.get_input(op, "CurrentStep"), ()).astype(
        "float32") if op.input("CurrentStep") else None)

    if len(sparsity) > 1 and step_in is not None:
        # reference warmup ramp: sparsity[i] holds for rampup_step /
        # len(sparsity) steps after rampup_begin_step; each branch has a
        # static top-k so shapes stay XLA-friendly
        per = max(rampup_step // len(sparsity), 1)
        idx = jnp.clip(((step_in - float(rampup)) // per).astype("int32"),
                       0, len(sparsity) - 1)
        u_dgc, v_dgc, send = jax.lax.switch(
            idx,
            [lambda u=u, v=v, g=g, s=s: dgc_lib.dgc_compress(
                u, v, g, m, 1.0 - float(s)) for s in sparsity])
    else:
        u_dgc, v_dgc, send = dgc_lib.dgc_compress(
            u, v, g, m, 1.0 - float(sparsity[-1]))

    if rampup > 0 and step_in is not None:
        use = (step_in >= float(rampup)).astype(g.dtype)
        keep = 1.0 - use
        u1 = m * u + g  # plain momentum velocity pre-rampup
        u_out = use * u_dgc + keep * u1
        v_out = use * v_dgc  # error feedback starts empty at rampup
        send = use * send + keep * u1
    else:
        u_out, v_out = u_dgc, v_dgc
    ctx.set_output(op, "UOut", u_out)
    ctx.set_output(op, "VOut", v_out)
    ctx.set_output(op, "GradOut", send)
