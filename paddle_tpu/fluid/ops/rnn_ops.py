"""Recurrent op lowerings — reference ``operators/lstm_op.cc``,
``gru_op.cc``, ``lstm_unit_op``, ``gru_unit_op``, ``cudnn_lstm_op``
(math/detail/lstm_kernel.h, gru_kernel.h give the exact gate equations).

TPU-native: ragged (bounded-LoD) inputs are packed to a padded
``[n_seq, T_bound]`` layout with plain gathers, the recurrence runs as ONE
``lax.scan`` over time (XLA compiles the body once; the MXU sees a
[n, H] x [H, 4H] matmul per tick), state updates are masked by
``t < length`` so padding ticks are identity, and the result is flattened
back to token rows. This replaces the reference's batch-reordering
``LoDTensor2BatchFunctor`` (math/sequence2batch.h) — no reorder pass, no
per-sequence kernel launches.

Gate layouts (must match the reference exactly):
  LSTM gates[4H] = [c~ ("in"), i, f, o]   (lstm_kernel.h:30)
      i/f/o get peephole terms checkI/F/O from prev or new cell state
      c_t = c~ * i + c_{t-1} * f ; h_t = o * act(c_t)
  GRU  gates[3H] = [u, r, c~]             (gru_kernel.h)
      c~ = act(x_c + (r . h_prev) W_c) ; h = (1-u) h_prev + u c~
      (origin_mode=True flips to h = u h_prev + (1-u) c~)
"""

import numpy as np

from ..registry import register
from .sequence_ops import _lod, _seg_info


def _act(name):
    import jax

    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jax.numpy.tanh,
        "relu": jax.nn.relu,
        "identity": (lambda x: x),
    }[str(name or "tanh")]


def _pack(x, lengths):
    """[total_bound, D] + lengths[n] -> padded [n, Tb, D], Tb = total bound."""
    import jax.numpy as jnp

    n = lengths.shape[0]
    T = x.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, T)
    pos = jnp.arange(T, dtype=np.dtype("int32"))[None, :]
    src = jnp.clip(starts[:, None] + pos, 0, T - 1)      # [n, Tb]
    inb = pos < lengths[:, None]
    out = jnp.where(inb[..., None], x[src], 0)
    return out, inb


def _unpack(h, lengths, total):
    """[n, Tb, D] -> flattened [total_bound, D] (tokens front-packed)."""
    import jax.numpy as jnp

    n = lengths.shape[0]
    seg, starts, cum, valid = _seg_info(lengths, total)
    tok = jnp.arange(total, dtype=np.dtype("int32"))
    pos = tok - starts[jnp.clip(seg, 0, n - 1)]
    out = h[jnp.clip(seg, 0, n - 1), jnp.clip(pos, 0, h.shape[1] - 1)]
    return jnp.where(valid[:, None], out, 0)


def _lstm_scan(gates_pad, mask, w_h, c0, h0, checks, cell_clip,
               act_gate, act_cell, act_cand, reverse):
    """gates_pad [n,T,4H] = x W (+bias) precomputed; returns h,c [n,T,H]."""
    import jax
    import jax.numpy as jnp

    n, T, H4 = gates_pad.shape
    H = H4 // 4
    checkI, checkF, checkO = checks
    t_axis = jnp.arange(T)
    if reverse:
        gates_pad = gates_pad[:, ::-1]
        mask = mask[:, ::-1]

    def step(carry, inp):
        h_prev, c_prev = carry
        g, m = inp  # g [n,4H], m [n]
        g = g + h_prev @ w_h
        cand = act_cand(g[:, :H])
        ig = act_gate(g[:, H:2 * H] + c_prev * checkI)
        fg = act_gate(g[:, 2 * H:3 * H] + c_prev * checkF)
        c = cand * ig + c_prev * fg
        if cell_clip and cell_clip > 0:
            c = jnp.clip(c, -cell_clip, cell_clip)
        og = act_gate(g[:, 3 * H:] + c * checkO)
        h = og * act_cell(c)
        m = m[:, None].astype(h.dtype)
        h = m * h + (1 - m) * h_prev
        c = m * c + (1 - m) * c_prev
        return (h, c), (h, c)

    (_, _), (hs, cs) = jax.lax.scan(
        step, (h0, c0), (gates_pad.transpose(1, 0, 2), mask.T))
    hs = hs.transpose(1, 0, 2)
    cs = cs.transpose(1, 0, 2)
    if reverse:
        hs, cs = hs[:, ::-1], cs[:, ::-1]
    return hs, cs


@register("dynamic_lstm")
@register("dynamic_lstmp")
def _dynamic_lstm(ctx, op):
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")        # [total, 4H] pre-projected
    w = ctx.get_input(op, "Weight")       # [H, 4H] (lstmp: [P, 4H])
    b = ctx.get_input(op, "Bias")         # [1, 4H] or [1, 7H] w/ peepholes
    proj = ctx.get_input(op, "ProjWeight")  # lstmp only: [H, P]
    lengths = _lod(ctx, op.input("Input")[0])
    n = lengths.shape[0]
    total = x.shape[0]
    H = w.shape[1] // 4
    P = proj.shape[1] if proj is not None else H
    use_peep = bool(op.attr("use_peepholes", True))
    act_gate = _act(op.attr("gate_activation", "sigmoid"))
    act_cell = _act(op.attr("cell_activation", "tanh"))
    act_cand = _act(op.attr("candidate_activation", "tanh"))
    reverse = bool(op.attr("is_reverse", False))
    cell_clip = float(op.attr("cell_clip", 0.0) or 0.0)

    gates = x
    if b is not None:
        gates = gates + b.reshape(-1)[:4 * H][None, :]
    if use_peep and b is not None and b.reshape(-1).shape[0] >= 7 * H:
        flat = b.reshape(-1)
        checks = (flat[4 * H:5 * H], flat[5 * H:6 * H], flat[6 * H:7 * H])
    else:
        checks = (jnp.zeros((H,), x.dtype),) * 3

    gpad, mask = _pack(gates, lengths)
    h0 = ctx.get_input(op, "H0")
    c0 = ctx.get_input(op, "C0")
    if h0 is None:
        h0 = jnp.zeros((n, P), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((n, H), x.dtype)

    if proj is None:
        hs, cs = _lstm_scan(gpad, mask, w, c0, h0, checks, cell_clip,
                            act_gate, act_cell, act_cand, reverse)
    else:
        # projection: recurrent input is r = act(h) @ proj, so fold the
        # projection into the scan
        import jax

        act_proj = _act(op.attr("proj_activation", "identity"))
        if reverse:
            gpad, mask = gpad[:, ::-1], mask[:, ::-1]

        def step(carry, inp):
            r_prev, c_prev = carry
            g, m = inp
            g = g + r_prev @ w
            cand = act_cand(g[:, :H])
            ig = act_gate(g[:, H:2 * H] + c_prev * checks[0])
            fg = act_gate(g[:, 2 * H:3 * H] + c_prev * checks[1])
            c = cand * ig + c_prev * fg
            if cell_clip > 0:
                c = jnp.clip(c, -cell_clip, cell_clip)
            og = act_gate(g[:, 3 * H:] + c * checks[2])
            h = og * act_cell(c)
            r = act_proj(h @ proj)
            m = m[:, None].astype(h.dtype)
            r = m * r + (1 - m) * r_prev
            c = m * c + (1 - m) * c_prev
            return (r, c), (r, c)

        (_, _), (hs, cs) = jax.lax.scan(
            step, (h0, c0), (gpad.transpose(1, 0, 2), mask.T))
        hs, cs = hs.transpose(1, 0, 2), cs.transpose(1, 0, 2)
        if reverse:
            hs, cs = hs[:, ::-1], cs[:, ::-1]

    hflat = _unpack(hs, lengths, total)
    cflat = _unpack(cs, lengths, total)
    out_slot = "Projection" if proj is not None else "Hidden"
    ctx.set_output(op, out_slot, hflat)
    ctx.set_output(op, "Cell", cflat)
    from ..lod import lod_name

    for slot in (out_slot, "Cell"):
        names = op.output(slot)
        if names:
            ctx.env[lod_name(names[0])] = lengths


@register("dynamic_gru")
def _dynamic_gru(ctx, op):
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")     # [total, 3H]
    w = ctx.get_input(op, "Weight")    # [H, 3H]
    b = ctx.get_input(op, "Bias")
    lengths = _lod(ctx, op.input("Input")[0])
    n = lengths.shape[0]
    total = x.shape[0]
    H = w.shape[0]
    act_gate = _act(op.attr("gate_activation", "sigmoid"))
    act_cand = _act(op.attr("activation", "tanh"))
    reverse = bool(op.attr("is_reverse", False))
    origin = bool(op.attr("origin_mode", False))

    gates = x if b is None else x + b.reshape(-1)[None, :]
    gpad, mask = _pack(gates, lengths)
    h0 = ctx.get_input(op, "H0")
    if h0 is None:
        h0 = jnp.zeros((n, H), x.dtype)
    w_ur = w[:, :2 * H]   # update+reset recurrent weights
    w_c = w[:, 2 * H:]
    if reverse:
        gpad, mask = gpad[:, ::-1], mask[:, ::-1]

    def step(h_prev, inp):
        g, m = inp
        ur = act_gate(g[:, :2 * H] + h_prev @ w_ur)
        u, r = ur[:, :H], ur[:, H:]
        cand = act_cand(g[:, 2 * H:] + (r * h_prev) @ w_c)
        if origin:
            h = u * h_prev + (1 - u) * cand
        else:
            h = (1 - u) * h_prev + u * cand
        m = m[:, None].astype(h.dtype)
        h = m * h + (1 - m) * h_prev
        return h, h

    _, hs = jax.lax.scan(step, h0, (gpad.transpose(1, 0, 2), mask.T))
    hs = hs.transpose(1, 0, 2)
    if reverse:
        hs = hs[:, ::-1]
    out = _unpack(hs, lengths, total)
    ctx.set_output(op, "Hidden", out)
    from ..lod import lod_name

    names = op.output("Hidden")
    if names:
        ctx.env[lod_name(names[0])] = lengths


@register("lstm_unit")
def _lstm_unit(ctx, op):
    """One LSTM step from pre-computed gates [B, 4H] (reference
    lstm_unit_op.cc: gate order i, f, c~, o with plain sigmoid/tanh)."""
    import jax
    import jax.numpy as jnp

    g = ctx.get_input(op, "X")
    c_prev = ctx.get_input(op, "C_prev")
    H = c_prev.shape[-1]
    forget_bias = float(op.attr("forget_bias", 0.0))
    i = jax.nn.sigmoid(g[:, :H])
    f = jax.nn.sigmoid(g[:, H:2 * H] + forget_bias)
    cand = jnp.tanh(g[:, 2 * H:3 * H])
    o = jax.nn.sigmoid(g[:, 3 * H:])
    c = f * c_prev + i * cand
    h = o * jnp.tanh(c)
    ctx.set_output(op, "C", c)
    ctx.set_output(op, "H", h)


@register("gru_unit")
def _gru_unit(ctx, op):
    """One GRU step (reference gru_unit_op.cc): gates [B, 3H] = x W + b,
    order (u, r, c~); h = prev - u*prev + u*c~ (origin_mode flips)."""
    import jax.numpy as jnp

    g = ctx.get_input(op, "Input")
    h_prev = ctx.get_input(op, "HiddenPrev")
    w = ctx.get_input(op, "Weight")
    b = ctx.get_input(op, "Bias")
    H = h_prev.shape[-1]
    act_gate = _act({1: "sigmoid", 2: "tanh", 3: "relu", 0: "identity"}.get(
        op.attr("gate_activation", 1), "sigmoid")
        if isinstance(op.attr("gate_activation", 1), int)
        else op.attr("gate_activation"))
    act_cand = _act({1: "sigmoid", 2: "tanh", 3: "relu", 0: "identity"}.get(
        op.attr("activation", 2), "tanh")
        if isinstance(op.attr("activation", 2), int)
        else op.attr("activation"))
    origin = bool(op.attr("origin_mode", False))
    if b is not None:
        g = g + b.reshape(-1)[None, :]
    ur = act_gate(g[:, :2 * H] + h_prev @ w[:, :2 * H])
    u, r = ur[:, :H], ur[:, H:]
    cand = act_cand(g[:, 2 * H:] + (r * h_prev) @ w[:, 2 * H:])
    if origin:
        h = u * h_prev + (1 - u) * cand
    else:
        h = (1 - u) * h_prev + u * cand
    ctx.set_output(op, "Gate", jnp.concatenate([u, r, cand], axis=1))
    ctx.set_output(op, "ResetHiddenPrev", r * h_prev)
    ctx.set_output(op, "Hidden", h)


@register("cudnn_lstm", has_state=True)
@register("lstm", has_state=True)
def _cudnn_lstm(ctx, op):
    """Multi-layer (optionally bidirectional-free) LSTM over PADDED
    [seq, batch, in] input — the reference's cudnn_lstm capability
    (cudnn_lstm_op.cc) as a stacked lax.scan."""
    import jax
    import jax.numpy as jnp

    x = ctx.get_input(op, "Input")          # [T, B, I]
    init_h = ctx.get_input(op, "InitH")     # [L, B, H]
    init_c = ctx.get_input(op, "InitC")
    w = ctx.get_input(op, "W")              # flat param blob
    hidden = int(op.attr("hidden_size"))
    layers = int(op.attr("num_layers", 1))
    T, B, I = x.shape
    off = 0
    outs = x
    last_h, last_c = [], []
    flat = w.reshape(-1)
    for layer in range(layers):
        in_dim = I if layer == 0 else hidden
        wx = flat[off:off + in_dim * 4 * hidden].reshape(in_dim, 4 * hidden)
        off += in_dim * 4 * hidden
        wh = flat[off:off + hidden * 4 * hidden].reshape(hidden, 4 * hidden)
        off += hidden * 4 * hidden
        bias = flat[off:off + 4 * hidden]
        off += 4 * hidden
        gates = outs @ wx + bias  # [T, B, 4H]
        h0, c0 = init_h[layer], init_c[layer]

        def step(carry, g, _wh=wh, _H=hidden):
            h_prev, c_prev = carry
            g = g + h_prev @ _wh
            # cudnn gate order i, f, c~, o
            i = jax.nn.sigmoid(g[:, :_H])
            f = jax.nn.sigmoid(g[:, _H:2 * _H])
            cand = jnp.tanh(g[:, 2 * _H:3 * _H])
            o = jax.nn.sigmoid(g[:, 3 * _H:])
            c = f * c_prev + i * cand
            h = o * jnp.tanh(c)
            return (h, c), h

        (hT, cT), hs = jax.lax.scan(step, (h0, c0), gates)
        outs = hs
        # inter-layer dropout (cudnn semantics: applied to every layer's
        # output except the last, training mode only)
        drop = float(op.attr("dropout_prob", 0.0) or 0.0)
        if drop > 0 and not op.attr("is_test", False) and \
                layer < layers - 1:
            keep = 1.0 - drop
            mask_d = jax.random.bernoulli(ctx.next_rng(), keep, outs.shape)
            outs = jnp.where(mask_d, outs / keep, 0.0)
        last_h.append(hT)
        last_c.append(cT)
    ctx.set_output(op, "Out", outs)
    ctx.set_output(op, "LastH", jnp.stack(last_h))
    ctx.set_output(op, "LastC", jnp.stack(last_c))


# ---------------------------------------------------------------------------
# beam search (dense redesign — reference beam_search_op.cc walks LoD
# levels on the host; here rows are [batch*beam] and selection is one
# reshaped top-k on the device)
# ---------------------------------------------------------------------------


@register("beam_pos")
def _beam_pos(ctx, op):
    """[B*beam, 1] int — each row's position within its beam group."""
    import jax.numpy as jnp

    ref = ctx.get_input(op, "X")
    b = int(op.attr("beam_size"))
    bw = ref.shape[0]
    ctx.set_output(op, "Out", (jnp.arange(bw, dtype=np.dtype("int32"))
                               % b)[:, None].astype(np.dtype("int32")))


@register("beam_search")
def _beam_search(ctx, op):
    import jax
    import jax.numpy as jnp

    pre_ids = ctx.get_input(op, "pre_ids").reshape(-1)        # [bw]
    pre_scores = ctx.get_input(op, "pre_scores").reshape(-1)  # [bw]
    scores = ctx.get_input(op, "scores")                      # [bw, V]
    b = int(op.attr("beam_size"))
    end_id = int(op.attr("end_id"))
    accumulated = bool(op.attr("is_accumulated", True))
    bw, V = scores.shape
    batch = bw // b
    if accumulated:
        acc = scores
    else:
        acc = pre_scores[:, None] + jnp.log(jnp.maximum(scores, 1e-30))
    # finished beams (pre_id == end_id) contribute exactly one candidate:
    # (end_id, pre_score) — they neither grow nor decay
    finished = (pre_ids == end_id)
    neg = jnp.asarray(-1e30, acc.dtype)
    end_onehot = (jnp.arange(V) == end_id)[None, :]
    fin_row = jnp.where(end_onehot, pre_scores[:, None], neg)
    acc = jnp.where(finished[:, None], fin_row, acc)

    flat = acc.reshape(batch, b * V)
    top_scores, top_idx = jax.lax.top_k(flat, b)              # [batch, b]
    parent_in_batch = top_idx // V
    token = top_idx % V
    batch_base = (jnp.arange(batch, dtype=np.dtype("int32")) * b)[:, None]
    parent = (parent_in_batch.astype(np.dtype("int32")) + batch_base)
    ctx.set_output(op, "selected_ids",
                   token.reshape(-1, 1).astype(np.dtype("int64")))
    ctx.set_output(op, "selected_scores",
                   top_scores.reshape(-1, 1).astype(np.dtype("float32")))
    ctx.set_output(op, "parent_idx", parent.reshape(-1))


@register("gather_tree")
def _gather_tree(ctx, op):
    """Backtrack beam parent pointers into full sequences (reference
    gather_tree_op.cc) — a reverse lax.scan carrying the live row pointer."""
    import jax
    import jax.numpy as jnp

    ids = ctx.get_input(op, "Ids")        # [T, BW] (or [T, B, beam])
    parents = ctx.get_input(op, "Parents")
    shape = ids.shape
    T = shape[0]
    flat_ids = ids.reshape(T, -1)
    flat_par = parents.reshape(T, -1).astype(np.dtype("int32"))
    BW = flat_ids.shape[1]

    def step(ptr, x):
        ids_t, par_t = x
        tokens = ids_t[ptr]
        return par_t[ptr], tokens

    init = jnp.arange(BW, dtype=np.dtype("int32"))
    _, toks = jax.lax.scan(step, init, (flat_ids[::-1], flat_par[::-1]))
    out = toks[::-1].reshape(shape)
    ctx.set_output(op, "Out", out)


@register("beam_search_decode")
def _beam_search_decode(ctx, op):
    """Emit final sequences + scores. Dense protocol: Ids [T, BW] are the
    per-step selected ids; optional Parents [T, BW] triggers gather_tree
    backtracking (the reference recovered parents from LoD)."""
    ids = ctx.get_input(op, "Ids")
    scores = ctx.get_input(op, "Scores")
    parents = ctx.get_input(op, "Parents")
    if parents is not None:
        import jax
        import jax.numpy as jnp

        T = ids.shape[0]
        flat_ids = ids.reshape(T, -1)
        flat_par = parents.reshape(T, -1).astype(np.dtype("int32"))

        def step(ptr, x):
            ids_t, par_t = x
            return par_t[ptr], ids_t[ptr]

        init = jnp.arange(flat_ids.shape[1], dtype=np.dtype("int32"))
        _, toks = jax.lax.scan(step, init,
                               (flat_ids[::-1], flat_par[::-1]))
        ids = toks[::-1].reshape(ids.shape)
    ctx.set_output(op, "SentenceIds", ids)
    ctx.set_output(op, "SentenceScores", scores)
