"""Lowerings for the sparse embedding engine (paddle_tpu.embedding).

``embedding_lookup`` — the device tier's dedup-gather: unique the batch's
flat ids (static ``size=`` so shapes stay XLA-closed), gather only the
unique rows, index the result back per position. Under GSPMD a gather from
a row-sharded table with replicated (small) indices lowers to a per-shard
partial gather + one all-reduce — all-to-all-free. Bit-identical to a
naive gather because rows are copied, never recomputed.

``host_embedding_lookup`` — the host tier's device half: a plain gather
from the fixed-shape resident cache param, indexed by the engine-computed
``<table>@SLOTS`` feed. The raw ids ride along only for the padding mask,
so the compiled step never depends on the vocabulary size.

Both honor ``ctx.sparse_eps`` (ops/autodiff.py): the additive eps at the
lookup output is how the backward reads a SelectedRows (rows, values)
cotangent without ever building a dense W-grad.
"""

import numpy as np

from ..registry import register


def _maybe_eps(ctx, op, out):
    eps_map = getattr(ctx, "sparse_eps", None)
    if eps_map is not None:
        eps = eps_map.get(op.output("Out")[0])
        if eps is not None:
            # before the padding mask, so padding positions get zero
            # cotangent exactly like the dense grad path
            out = out + eps
    return out


def _squeeze_ids(ids):
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return ids


@register("embedding_lookup")
def _embedding_lookup(ctx, op):
    import jax.numpy as jnp

    w = ctx.get_input(op, "W")
    ids = _squeeze_ids(ctx.get_input(op, "Ids"))
    idx = ids.astype(np.dtype("int32"))
    flat = idx.reshape(-1)
    if op.attr("dedup", True) and flat.shape[0] > 1:
        # fill_value=0 keeps padded lanes in-range; their gathered rows are
        # never indexed because inv only points at real lanes
        uniq, inv = jnp.unique(flat, return_inverse=True,
                               size=flat.shape[0], fill_value=0)
        rows = jnp.take(w, uniq, axis=0)
        out = jnp.take(rows, inv.reshape(-1).astype(np.dtype("int32")),
                       axis=0).reshape(idx.shape + w.shape[1:])
    else:
        out = jnp.take(w, idx, axis=0)
    out = _maybe_eps(ctx, op, out)
    padding_idx = op.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    ctx.set_output(op, "Out", out)


@register("host_embedding_lookup")
def _host_embedding_lookup(ctx, op):
    import jax.numpy as jnp

    w = ctx.get_input(op, "W")  # resident cache, [budget + 1, dim]
    slots = _squeeze_ids(ctx.get_input(op, "Ids"))
    out = jnp.take(w, slots.astype(np.dtype("int32")), axis=0)
    out = _maybe_eps(ctx, op, out)
    padding_idx = op.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0 and op.input("RawIds"):
        raw = _squeeze_ids(ctx.get_input(op, "RawIds"))
        out = jnp.where((raw == padding_idx)[..., None], 0.0, out)
    ctx.set_output(op, "Out", out)


@register("host_embedding_init")
def _host_embedding_init(ctx, op):
    """(Re-)initialize a host table's device residency — placed in the
    STARTUP program by ``layers.embedding`` (host tier) so
    ``exe.run(startup)`` forgets the cache exactly like it re-initializes
    device parameters. Executed eagerly by the Executor's host-op scan,
    NOT in the compiled program: an in-program io_callback fires on an
    XLA runtime thread after the async dispatch returns, racing the next
    step's residency prepare() and wiping a freshly-admitted LUT."""
