"""In-graph metric ops: accuracy, auc, precision/recall.

Parity: reference ``operators/metrics/{accuracy,auc,precision_recall}_op``.
AUC keeps persistable histogram stats updated in-graph, like the reference's
stat vars.
"""

import numpy as np

from ..registry import register


@register("accuracy")
def _accuracy(ctx, op):
    import jax.numpy as jnp

    pred_idx = ctx.get_input(op, "Indices")  # (N, k) from top_k
    label = ctx.get_input(op, "Label")
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[..., 0]
    correct = jnp.any(pred_idx == label[:, None].astype(pred_idx.dtype), axis=1)
    num_correct = jnp.sum(correct.astype(np.dtype("float32")))
    total = pred_idx.shape[0]
    ctx.set_output(op, "Accuracy", num_correct / total)
    ctx.set_output(op, "Correct", num_correct.astype(np.dtype("int32")))
    ctx.set_output(op, "Total", jnp.asarray(total, dtype=np.dtype("int32")))


@register("auc")
def _auc(ctx, op):
    import jax.numpy as jnp

    preds = ctx.get_input(op, "Predict")  # (N, 2) binary probs
    label = ctx.get_input(op, "Label")
    stat_pos = ctx.get_input(op, "StatPos")
    stat_neg = ctx.get_input(op, "StatNeg")
    num_thresholds = op.attr("num_thresholds", 4095)
    pos_prob = preds[:, 1] if preds.ndim == 2 else preds
    if label.ndim == 2:
        label = label[..., 0]
    bucket = jnp.clip(
        (pos_prob * num_thresholds).astype(np.dtype("int32")), 0, num_thresholds
    )
    is_pos = (label > 0).astype(stat_pos.dtype)
    new_pos = stat_pos.at[bucket].add(is_pos)
    new_neg = stat_neg.at[bucket].add(1.0 - is_pos)
    # AUC via trapezoid over threshold histogram (descending threshold)
    pos_flip = jnp.flip(new_pos)
    neg_flip = jnp.flip(new_neg)
    tp = jnp.cumsum(pos_flip)
    fp = jnp.cumsum(neg_flip)
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros((1,), tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros((1,), fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg + 1e-12), 0.0)
    ctx.set_output(op, "AUC", auc)
    names = op.output("StatPosOut")
    if names:
        ctx.set(names[0], new_pos)
    names = op.output("StatNegOut")
    if names:
        ctx.set(names[0], new_neg)


@register("mean_iou")
def _mean_iou(ctx, op):
    import jax.numpy as jnp

    pred = ctx.get_input(op, "Predictions").reshape(-1).astype(np.dtype("int32"))
    label = ctx.get_input(op, "Labels").reshape(-1).astype(np.dtype("int32"))
    num_classes = op.attr("num_classes")
    inter = jnp.zeros((num_classes,), np.dtype("float32")).at[
        jnp.where(pred == label, pred, num_classes - 1)
    ].add(jnp.where(pred == label, 1.0, 0.0))
    pred_cnt = jnp.zeros((num_classes,), np.dtype("float32")).at[pred].add(1.0)
    lab_cnt = jnp.zeros((num_classes,), np.dtype("float32")).at[label].add(1.0)
    union = pred_cnt + lab_cnt - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(iou.dtype)), 1.0)
    ctx.set_output(op, "OutMeanIou", mean)
    ctx.set_output(op, "OutWrong", (pred_cnt - inter).astype(np.dtype("int32")))
    ctx.set_output(op, "OutCorrect", inter.astype(np.dtype("int32")))
