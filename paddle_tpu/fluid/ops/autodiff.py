"""The ``autodiff`` op: gradient computation as a functional transform.

TPU-first replacement for the reference's per-op grad machinery
(``GradOpDescMakerBase`` grad_op_desc_maker.h + ``backward.py:933``'s
op-by-op grad program synthesis): instead of synthesizing hundreds of
``*_grad`` ops, ``append_backward`` inserts ONE ``autodiff`` op whose
lowering replays the forward ops as a pure function and differentiates it
with ``jax.grad``. XLA CSEs the replayed forward against the original
computation, so no work is duplicated in the compiled executable.

Random ops replay with recorded PRNG keys (``LowerCtx.replay_keys``) so the
differentiated forward is bit-identical to the primal (the reference saves
dropout masks for backward — same guarantee, zero memory cost here because
XLA dedups).

``stop_gradient`` var markers are honored by wrapping those vars in
``lax.stop_gradient`` during the replay.
"""

from ..registry import LowerCtx, register, registry


def _run_ops(rctx, ops, wrt_names):
    """Lower `ops` in order on rctx, honoring stop_gradient markers."""
    import jax

    from ..registry import lower_op

    for o in ops:
        lower_op(rctx, o)
        for name in o.output_arg_names():
            v = rctx.var(name)
            if v is not None and v.stop_gradient and name not in wrt_names:
                rctx.env[name] = jax.lax.stop_gradient(rctx.env[name])


def _replay_forward_checkpointed(ctx, prior_ops, wrt_names, overrides,
                                 checkpoints):
    """Replay the forward split into segments at the checkpoint vars, each
    wrapped in ``jax.checkpoint`` so XLA saves only segment boundaries and
    rematerializes intermediate activations during the backward pass
    (reference recompute: ``backward.py:576``
    ``_append_backward_ops_with_checkpoints_``).

    Only the loss needs to survive to the caller: each segment returns just
    the env entries later segments (or the loss) consume, so the residual
    set the grad transform saves is exactly those boundary values.
    """
    import jax

    # segment boundaries: after the op that (last) produces each checkpoint
    producer = {}
    for i, o in enumerate(prior_ops):
        for name in o.output_arg_names():
            producer[name] = i
    cut_idx = sorted({producer[c] for c in checkpoints if c in producer})
    segments = []
    start = 0
    for ci in cut_idx:
        segments.append(prior_ops[start:ci + 1])
        start = ci + 1
    if start < len(prior_ops):
        segments.append(prior_ops[start:])
    if len(segments) <= 1:
        renv = _replay_forward(ctx, prior_ops, wrt_names, overrides)
        return renv

    # vars each later segment reads (so each segment's output pytree is the
    # minimal carry); key slices per segment from the primal lowering record
    spans = ctx.op_key_spans
    all_keys = list(ctx.used_keys)
    seg_keys, seg_needs = [], []
    for seg in segments:
        ks = [spans.get(id(o), (0, 0)) for o in seg]
        lo = min((s for s, _ in ks), default=0)
        hi = max((e for _, e in ks), default=0)
        seg_keys.append(all_keys[lo:hi])
        seg_needs.append(set())
    for i in range(len(segments)):
        for later in segments[i + 1:]:
            for o in later:
                seg_needs[i].update(o.input_arg_names())

    env = dict(ctx.initial_env)
    env.update(overrides)
    for i, seg in enumerate(segments):
        keep = seg_needs[i]
        is_last = i == len(segments) - 1

        def run_seg(env_in, _seg=seg, _keys=seg_keys[i], _keep=keep,
                    _last=is_last):
            rctx = LowerCtx(ctx.block, dict(env_in), ctx.initial_rng,
                            mesh=ctx.mesh, replay_keys=list(_keys))
            rctx.initial_env = ctx.initial_env
            rctx.initial_rng = ctx.initial_rng
            _run_ops(rctx, _seg, wrt_names)
            if _last:
                return rctx.env
            out = dict(env_in)
            for k in _keep:
                if k in rctx.env:
                    out[k] = rctx.env[k]
            return out

        if is_last:
            env = run_seg(env)
        else:
            env = jax.checkpoint(run_seg)(env)
    return env


def _replay_forward(ctx, prior_ops, wrt_names, overrides, sparse_eps=None):
    """Build env after replaying prior_ops with wrt vars overridden.
    ``sparse_eps``: {param_name: zeros-like-lookup-out} injected additively
    into that param's lookup output during replay, so the cotangent w.r.t.
    eps IS the SelectedRows values gradient (no dense W-grad ever built)."""
    renv = dict(ctx.initial_env)
    renv.update(overrides)
    rctx = LowerCtx(
        ctx.block,
        renv,
        ctx.initial_rng,
        mesh=ctx.mesh,
        replay_keys=list(ctx.used_keys),
    )
    rctx.initial_env = ctx.initial_env
    rctx.initial_rng = ctx.initial_rng
    if sparse_eps:
        rctx.sparse_eps = sparse_eps
    _run_ops(rctx, prior_ops, wrt_names)
    return renv


@register("autodiff")
def _autodiff(ctx, op):
    import jax

    loss_name = op.attr("loss")
    wrt_names = list(op.attr("wrt"))
    grad_names = list(op.attr("grad_names"))
    loss_scale = op.attr("loss_scale", 1.0)
    # AMP dynamic loss scaling: the scale is a runtime *variable* (reference
    # decorator.py:135 multiplies the loss by the loss_scaling var), so the
    # dynamically updated value takes effect on the next step — a static
    # attr would freeze the scale at its initial value.
    scale_var = op.attr("loss_scale_var", None)
    if scale_var is not None:
        import jax.numpy as jnp

        # composes with the static attr (e.g. GradAllReduce's 1/nranks)
        loss_scale = loss_scale * jnp.reshape(
            jax.lax.stop_gradient(ctx.get(scale_var)), ()).astype("float32")

    block = ctx.block
    idx = next(i for i, o in enumerate(block.ops) if o is op)
    prior_ops = block.ops[:idx]

    wrt_vals = []
    for n in wrt_names:
        v = ctx.initial_env.get(n)
        if v is None:
            v = ctx.get(n)
        wrt_vals.append(v)

    checkpoints = op.attr("checkpoints", None)
    sparse_wrt = op.attr("sparse_wrt", None) or []
    # host-table (parameter-server) lookups: no device param, the cotangent
    # at the lookup output is PUSHED to the host store (ops/distributed_ops)
    dist_push = op.attr("dist_push", None) or []
    sparse_names = {s[0] for s in sparse_wrt}
    dense_idx = [i for i, n in enumerate(wrt_names) if n not in sparse_names]
    dense_names = [wrt_names[i] for i in dense_idx]

    def run_fwd(overrides, sparse_eps):
        if checkpoints:
            if sparse_eps:
                raise NotImplementedError(
                    "recompute + sparse embedding grads not supported yet")
            renv = _replay_forward_checkpointed(
                ctx, prior_ops, set(wrt_names), overrides, list(checkpoints))
        else:
            renv = _replay_forward(ctx, prior_ops, set(wrt_names), overrides,
                                   sparse_eps)
        loss = renv[loss_name]
        if loss.ndim > 0:
            import jax.numpy as jnp

            loss = jnp.sum(loss)
        return loss * loss_scale

    if sparse_wrt or dist_push:
        import numpy as np
        import jax.numpy as jnp

        # eps keyed by lookup OUTPUT name (unique per lookup op; works for
        # host-table lookups which have no W input)
        eps_outs = [s[2] for s in sparse_wrt] + [d[2] for d in dist_push]
        eps0 = [jnp.zeros_like(ctx.get(o)) for o in eps_outs]
        dense_vals = [wrt_vals[i] for i in dense_idx]

        def fwd2(dvals, evals):
            eps_map = dict(zip(eps_outs, evals))
            return run_fwd(dict(zip(dense_names, dvals)), eps_map)

        gdense, geps = jax.grad(fwd2, argnums=(0, 1))(dense_vals, eps0)
        for i, g in zip(dense_idx, gdense):
            ctx.set(grad_names[i], g)
        n_sparse = len(sparse_wrt)
        for (pname, ids_name, _), ge in zip(sparse_wrt, geps[:n_sparse]):
            ids = ctx.get(ids_name)
            rows = jnp.reshape(ids, (-1,)).astype("int32")
            values = jnp.reshape(ge, (rows.shape[0], -1))
            gname = grad_names[wrt_names.index(pname)]
            ctx.set(gname, values)
            ctx.set(gname + "@ROWS", rows)
        for (tname, ids_name, out_name, lr, optname), ge in zip(
                dist_push, geps[n_sparse:]):
            # bind the cotangent; the actual host push is a separate
            # `distributed_push` op appended after the autodiff op, so AMP
            # can unscale/overflow-gate the payload before it leaves the
            # device (ops/distributed_ops.py)
            ids = ctx.get(ids_name)
            # int32 on device (x64 is disabled; widening happens at the host
            # boundary in table.push — host tables beyond 2^31 rows would
            # need int64 device ids, which the chip doesn't carry anyway)
            rows = jnp.reshape(ids, (-1,)).astype(np.dtype("int32"))
            values = jnp.reshape(
                ge.astype(np.dtype("float32")), (rows.shape[0], -1))
            ctx.set(out_name + "@PS_GRAD", values)
            ctx.set(out_name + "@PS_ROWS", rows)
    else:
        grads = jax.grad(lambda vals: run_fwd(dict(zip(wrt_names, vals)),
                                              None))(wrt_vals)
        for gname, g in zip(grad_names, grads):
            ctx.set(gname, g)


@register("calc_gradient")
def _calc_gradient(ctx, op):
    """Grad of arbitrary targets w.r.t. arbitrary inputs with optional
    user-supplied target gradients (reference ``backward.py:1199``)."""
    import jax

    target_names = list(op.attr("targets"))
    wrt_names = list(op.attr("wrt"))
    grad_names = list(op.attr("grad_names"))
    tg_names = op.attr("target_gradients") or []

    block = ctx.block
    idx = next(i for i, o in enumerate(block.ops) if o is op)
    prior_ops = block.ops[:idx]

    wrt_vals = []
    for n in wrt_names:
        v = ctx.initial_env.get(n)
        if v is None:
            v = ctx.get(n)
        wrt_vals.append(v)

    def fwd(vals):
        renv = _replay_forward(ctx, prior_ops, set(wrt_names), dict(zip(wrt_names, vals)))
        return [renv[t] for t in target_names]

    _, vjp_fn = jax.vjp(fwd, wrt_vals)
    if tg_names:
        cotangents = [ctx.get(n) for n in tg_names]
    else:
        import jax.numpy as jnp

        cotangents = [jnp.ones_like(ctx.get(t)) for t in target_names]
    (grads,) = vjp_fn(cotangents)
    for gname, g in zip(grad_names, grads):
        ctx.set(gname, g)
