"""The ``autodiff`` op: gradient computation as a functional transform.

TPU-first replacement for the reference's per-op grad machinery
(``GradOpDescMakerBase`` grad_op_desc_maker.h + ``backward.py:933``'s
op-by-op grad program synthesis): instead of synthesizing hundreds of
``*_grad`` ops, ``append_backward`` inserts ONE ``autodiff`` op whose
lowering replays the forward ops as a pure function and differentiates it
with ``jax.grad``. XLA CSEs the replayed forward against the original
computation, so no work is duplicated in the compiled executable.

Random ops replay with recorded PRNG keys (``LowerCtx.replay_keys``) so the
differentiated forward is bit-identical to the primal (the reference saves
dropout masks for backward — same guarantee, zero memory cost here because
XLA dedups).

``stop_gradient`` var markers are honored by wrapping those vars in
``lax.stop_gradient`` during the replay.
"""

from ..registry import LowerCtx, register, registry


def _replay_forward(ctx, prior_ops, wrt_names, overrides):
    """Build env after replaying prior_ops with wrt vars overridden."""
    import jax

    renv = dict(ctx.initial_env)
    renv.update(overrides)
    rctx = LowerCtx(
        ctx.block,
        renv,
        ctx.initial_rng,
        mesh=ctx.mesh,
        replay_keys=list(ctx.used_keys),
    )
    rctx.initial_env = ctx.initial_env
    rctx.initial_rng = ctx.initial_rng
    for o in prior_ops:
        registry.get(o.type).lower(rctx, o)
        for name in o.output_arg_names():
            v = rctx.var(name)
            if v is not None and v.stop_gradient and name not in wrt_names:
                renv[name] = jax.lax.stop_gradient(renv[name])
    return renv


@register("autodiff")
def _autodiff(ctx, op):
    import jax

    loss_name = op.attr("loss")
    wrt_names = list(op.attr("wrt"))
    grad_names = list(op.attr("grad_names"))
    loss_scale = op.attr("loss_scale", 1.0)

    block = ctx.block
    idx = next(i for i, o in enumerate(block.ops) if o is op)
    prior_ops = block.ops[:idx]

    wrt_vals = []
    for n in wrt_names:
        v = ctx.initial_env.get(n)
        if v is None:
            v = ctx.get(n)
        wrt_vals.append(v)

    def fwd(vals):
        renv = _replay_forward(ctx, prior_ops, set(wrt_names), dict(zip(wrt_names, vals)))
        loss = renv[loss_name]
        if loss.ndim > 0:
            import jax.numpy as jnp

            loss = jnp.sum(loss)
        return loss * loss_scale

    grads = jax.grad(fwd)(wrt_vals)
    for gname, g in zip(grad_names, grads):
        ctx.set(gname, g)


@register("calc_gradient")
def _calc_gradient(ctx, op):
    """Grad of arbitrary targets w.r.t. arbitrary inputs with optional
    user-supplied target gradients (reference ``backward.py:1199``)."""
    import jax

    target_names = list(op.attr("targets"))
    wrt_names = list(op.attr("wrt"))
    grad_names = list(op.attr("grad_names"))
    tg_names = op.attr("target_gradients") or []

    block = ctx.block
    idx = next(i for i, o in enumerate(block.ops) if o is op)
    prior_ops = block.ops[:idx]

    wrt_vals = []
    for n in wrt_names:
        v = ctx.initial_env.get(n)
        if v is None:
            v = ctx.get(n)
        wrt_vals.append(v)

    def fwd(vals):
        renv = _replay_forward(ctx, prior_ops, set(wrt_names), dict(zip(wrt_names, vals)))
        return [renv[t] for t in target_names]

    _, vjp_fn = jax.vjp(fwd, wrt_vals)
    if tg_names:
        cotangents = [ctx.get(n) for n in tg_names]
    else:
        import jax.numpy as jnp

        cotangents = [jnp.ones_like(ctx.get(t)) for t in target_names]
    (grads,) = vjp_fn(cotangents)
    for gname, g in zip(grad_names, grads):
        ctx.set(gname, g)
