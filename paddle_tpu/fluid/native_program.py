"""Python face of the native ProgramDesc IR library.

``native/program_graph.cc`` re-expresses the reference's native desc /
graph tier (``program_desc.h:30``, ``prune.h``, ``ir/graph_helper.*``,
``ir/graph_viz_pass.cc``) in C++ over the framework.proto wire format.
This module wraps it behind the same failure contract as the rest of
the native tier: every entry degrades to ``None`` when the toolchain is
absent, so callers must treat the native path as an accelerator /
cross-checker, never the only implementation (the Python Program is
authoritative).

Used by ``io.save_inference_model`` as a structural cross-check of the
pruned program before it hits disk, and by tests to pin that the C++
prune/lint agree with the Python implementations they mirror.
"""

import ctypes


def _lib():
    from .. import native

    return native.load_program_graph()


class NativeProgram(object):
    """A parsed ProgramDesc handle in the native library.

    Construct with :meth:`from_bytes` (wire bytes) or
    :meth:`from_program` (a fluid Program). Both return ``None`` when
    the native library is unavailable; ``from_bytes`` raises
    ``ValueError`` on malformed bytes.
    """

    def __init__(self, lib, handle):
        self._lib = lib
        self._h = handle

    @classmethod
    def from_bytes(cls, data):
        lib = _lib()
        if lib is None:
            return None
        h = lib.prg_parse(data, len(data))
        if not h:
            raise ValueError("native parse failed: %s" %
                             lib.prg_last_error().decode())
        return cls(lib, h)

    @classmethod
    def from_program(cls, program):
        return cls.from_bytes(program.serialize_to_string())

    def __del__(self):
        h, self._h = self._h, 0
        if h:
            self._lib.prg_destroy(h)

    # -- structure ----------------------------------------------------------
    @property
    def version(self):
        return self._lib.prg_version(self._h)

    @property
    def num_blocks(self):
        return self._lib.prg_num_blocks(self._h)

    def num_ops(self, block=0):
        return self._lib.prg_num_ops(self._h, block)

    def num_vars(self, block=0):
        return self._lib.prg_num_vars(self._h, block)

    def op_types(self, block=0):
        buf = ctypes.create_string_buffer(512)
        out = []
        for i in range(self.num_ops(block)):
            rc = self._lib.prg_op_type(self._h, block, i, buf, len(buf))
            out.append(buf.value.decode() if rc == 0 else "?")
        return out

    # -- transforms / reports -----------------------------------------------
    def _take_buf(self, ptr, nbytes=None):
        if not ptr:
            return b""
        data = (ctypes.string_at(ptr, nbytes) if nbytes is not None
                else ctypes.string_at(ptr))
        self._lib.prg_free(ptr)
        return data

    def serialize(self):
        """Canonical proto3 re-serialization of the parsed program."""
        out = ctypes.POINTER(ctypes.c_char)()
        n = ctypes.c_int64()
        rc = self._lib.prg_serialize(self._h, ctypes.byref(out),
                                     ctypes.byref(n))
        if rc != 0:
            raise RuntimeError("prg_serialize failed: %d" % rc)
        return self._take_buf(out, n.value)

    def prune(self, targets):
        """New NativeProgram holding the program pruned to ``targets``
        (same semantics as ``Program._prune``)."""
        if isinstance(targets, str):
            targets = [targets]
        arr = (ctypes.c_char_p * len(targets))(
            *[t.encode() for t in targets])
        h = self._lib.prg_prune(self._h, arr, len(targets))
        if not h:
            raise RuntimeError("prg_prune failed: %s" %
                               self._lib.prg_last_error().decode())
        return NativeProgram(self._lib, h)

    def lint(self):
        """List of issue strings ("E: ..." defects, "W: ..." advisory).

        The native count return is not cross-checked against the line
        split: a defect message quotes var names verbatim, so a
        pathological name containing a newline may split one issue into
        two lines — the lines are still the full report.
        """
        out = ctypes.POINTER(ctypes.c_char)()
        self._lib.prg_lint(self._h, ctypes.byref(out))
        text = self._take_buf(out).decode()
        return [l for l in text.splitlines() if l]

    def last_use(self, block=0):
        """Eager-deletion plan: {op_index: [var, ...]} — after which op
        each non-persistable declared var is dead (reference
        reference_count_pass semantics; advisory under XLA)."""
        out = ctypes.POINTER(ctypes.c_char)()
        rc = self._lib.prg_last_use(self._h, block, ctypes.byref(out))
        if rc != 0:
            raise RuntimeError("prg_last_use failed: %d" % rc)
        plan = {}
        # one "<op_idx>\x1f<name>" record per dead var (see
        # program_graph.cc last_use_plan)
        for line in self._take_buf(out).decode().splitlines():
            idx, _, name = line.partition("\x1f")
            plan.setdefault(int(idx), []).append(name)
        return plan

    def to_dot(self, block=0):
        """Graphviz digraph source for one block."""
        out = ctypes.POINTER(ctypes.c_char)()
        rc = self._lib.prg_to_dot(self._h, block, ctypes.byref(out))
        if rc != 0:
            raise RuntimeError("prg_to_dot failed: %d" % rc)
        return self._take_buf(out).decode()


def check_program_native(program):
    """Structural lint of ``program`` via the native library.

    Returns the list of "E: " defect lines (advisory "W: " lines are
    dropped), or ``None`` when the native library is unavailable —
    callers must not treat None as a pass.
    """
    np_ = NativeProgram.from_program(program)
    if np_ is None:
        return None
    return [i for i in np_.lint() if i.startswith("E: ")]
