"""paddle_tpu.fluid — the static-graph framework core.

Capability parity with the reference's ``python/paddle/fluid`` package,
executed by lowering Programs to XLA (see ``executor.py``).
"""

from . import (  # noqa: F401
    average,
    backward,
    clip,
    compat,
    contrib,
    compiler,
    data_feeder,
    dataset,
    debugger,
    evaluator,
    executor,
    flags,
    framework,
    initializer,
    install_check,
    io,
    layers,
    metrics,
    net_drawer,
    nets,
    optimizer,
    param_attr,
    passes,
    profiler,
    regularizer,
    transpiler,
    unique_name,
)
from .backward import append_backward, calc_gradient, gradients  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from . import core  # noqa: F401  (fluid.core.EOFException etc.)
from .data_feeder import DataFeeder  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from .reader import DataLoader, PyReader  # noqa: F401
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .lod import LoDTensor, create_lod_tensor  # noqa: F401
from .framework import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
    record_op_callstacks,
)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)


class CPUPlace:
    """Device tags (reference ``platform/place.h:26``). Placement is
    controlled by JAX backends; these are advisory."""

    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id


# CUDAPlace alias maps to the accelerator (TPU) for script compatibility
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    import jax

    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data (reference 1.6 new-style): shape given verbatim."""
    return layers.io.data(name, shape, dtype=dtype, append_batch_size=False,
                          lod_level=lod_level)
