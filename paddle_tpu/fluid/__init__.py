"""paddle_tpu.fluid — the static-graph framework core.

Capability parity with the reference's ``python/paddle/fluid`` package,
executed by lowering Programs to XLA (see ``executor.py``).
"""

from . import (  # noqa: F401
    average,
    backward,
    clip,
    communicator,
    compat,
    contrib,
    compiler,
    data_feeder,
    dataset,
    debugger,
    distribute_lookup_table,
    dygraph_grad_clip,
    evaluator,
    executor,
    faults,
    flags,
    framework,
    initializer,
    input,
    install_check,
    io,
    layers,
    lod_tensor,
    metrics,
    monitor,
    net_drawer,
    nets,
    optimizer,
    param_attr,
    passes,
    profiler,
    regularizer,
    resilience,
    transpiler,
    unique_name,
)
from .backward import append_backward, calc_gradient, gradients  # noqa: F401
from .transpiler import memory_optimize, release_memory  # noqa: F401
from .lod_tensor import create_random_int_lodtensor  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from . import core  # noqa: F401  (fluid.core.EOFException etc.)
from .data_feeder import DataFeeder  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from .reader import DataLoader, PyReader  # noqa: F401
from .executor import (  # noqa: F401
    Executor,
    Scope,
    global_scope,
    register_run_hook,
    scope_guard,
    unregister_run_hook,
)
from .flags import get_flags, set_flags  # noqa: F401
from .lod import LoDTensor, LoDTensorArray, create_lod_tensor  # noqa: F401
from .data_feed_desc import DataFeedDesc  # noqa: F401
from . import incubate  # noqa: F401
from .framework import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
    record_op_callstacks,
)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)


class CPUPlace:
    """Device tags (reference ``platform/place.h:26``). Placement is
    controlled by JAX backends; these are advisory."""

    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id


# CUDAPlace alias maps to the accelerator (TPU) for script compatibility
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    import jax

    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data (reference 1.6 new-style): shape given verbatim."""
    return layers.io.data(name, shape, dtype=dtype, append_batch_size=False,
                          lod_level=lod_level)


from .framework import name_scope  # noqa: F401,E402
from .io import load, save  # noqa: F401,E402

# 1.6 top-level layer aliases (fluid.embedding / fluid.one_hot)
embedding = layers.embedding
one_hot = layers.one_hot


def cpu_places(device_count=None):
    """Reference semantics: count from the arg, else CPU_NUM env."""
    import os as _os

    if device_count is None:
        device_count = int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(max(1, int(device_count)))]


def cuda_places(device_ids=None):
    """Accelerator places — TPU chips here (CUDAPlace aliases TPUPlace)."""
    import jax

    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [CUDAPlace(i) for i in ids]


import contextlib as _contextlib  # noqa: E402


@_contextlib.contextmanager
def device_guard(device=None):
    """Reference op-placement hint; placement is XLA's here — no-op."""
    yield


def load_op_library(lib_path):
    """The reference loads custom C++ op libraries; custom ops here
    register Python lowerings via ``paddle_tpu.fluid.registry.register``
    (host-native pieces ride ctypes — see paddle_tpu/native)."""
    raise NotImplementedError(
        "custom ops register via paddle_tpu.fluid.registry.register "
        "(JAX lowering) + ctypes for host-native code; there is no "
        "paddle C++ OpKernel ABI in this build")


from .framework import in_dygraph_mode  # noqa: F401,E402


def require_version(min_version, max_version=None):
    """Reference ``fluid.require_version``: checks the FRAMEWORK version
    this build tracks (capability parity with 1.6.x)."""
    def parse(v):
        out = []
        for x in str(v).split(".")[:3]:
            digits = ""
            for ch in x:
                if not ch.isdigit():
                    break
                digits += ch
            out.append(int(digits or 0))
        while len(out) < 3:
            out.append(0)           # zero-pad: "1.6" == 1.6.0 series
        return tuple(out)

    ours = parse(_TRACKED_VERSION)
    if parse(min_version) > ours:
        raise Exception(
            "this build tracks fluid %s < required %s"
            % (_TRACKED_VERSION, min_version))
    if max_version is not None and parse(max_version) < ours:
        raise Exception(
            "this build tracks fluid %s > allowed %s"
            % (_TRACKED_VERSION, max_version))


from ..version import full_version as _TRACKED_VERSION  # noqa: E402
