"""User-side dataset-file generators (reference
``python/paddle/fluid/incubate/data_generator/__init__.py``).

Subclass, implement ``generate_sample(line)`` returning a generator that
yields ``[(slot_name, [values...]), ...]`` per instance, then pipe raw
records through ``run_from_stdin()`` (the reference's contract: dataset
preprocessing jobs run these scripts under the ingestion engine) or call
``run_from_memory()``. The emitted text is the multislot line format the
dataset engine parses (``fluid/dataset.py`` / ``native/data_feed.cc``):
per slot ``<num> <v1> ... <vnum>``."""

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._line_limit = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    # -- user hooks ---------------------------------------------------------
    def generate_sample(self, line):
        """Return a generator yielding one or more instances for ``line``;
        each instance is ``[(slot_name, [values...]), ...]``."""
        raise NotImplementedError(
            "subclasses must implement generate_sample(line)")

    def generate_batch(self, samples):
        """Optional batch-level hook: yields instances given a list of them
        (default passthrough, reference ``data_generator:batch``)."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- serialization ------------------------------------------------------
    def _gen_str(self, instance):
        raise NotImplementedError

    # -- drivers ------------------------------------------------------------
    def run_from_stdin(self):
        """stdin raw lines -> stdout multislot lines."""
        batch = []
        for line in sys.stdin:
            gen = self.generate_sample(line)
            if gen is None:
                continue
            for instance in gen():
                batch.append(instance)
                if len(batch) >= self.batch_size_:
                    for ins in self.generate_batch(batch)():
                        sys.stdout.write(self._gen_str(ins))
                    batch = []
        for ins in self.generate_batch(batch)():
            sys.stdout.write(self._gen_str(ins))

    def run_from_memory(self, lines=None):
        """Like run_from_stdin but takes/returns python objects; returns the
        list of emitted text lines. With no ``lines``, ``generate_sample``
        is called once with ``None`` (the reference's memory-generation
        contract) — implement that case if you use this mode."""
        out = []
        batch = []

        def flush():
            for ins in self.generate_batch(batch)():
                out.append(self._gen_str(ins))

        for line in (lines if lines is not None else [None]):
            gen = self.generate_sample(line)
            if gen is None:
                continue
            for instance in gen():
                batch.append(instance)
                if len(batch) >= self.batch_size_:
                    flush()
                    batch = []
        flush()
        return out


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: values are ints (feasigns) or floats."""

    def _gen_str(self, instance):
        parts = []
        for name, values in instance:
            if not values:
                raise ValueError("slot %r has no values" % (name,))
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String-token slots: values are pre-stringified tokens."""

    def _gen_str(self, instance):
        parts = []
        for name, values in instance:
            if not values:
                raise ValueError("slot %r has no values" % (name,))
            parts.append(str(len(values)))
            parts.extend(values)
        return " ".join(parts) + "\n"
