"""Fleet abstract base (reference ``incubate/fleet/base/fleet_base.py:38``:
is_worker:85, is_server:139, init:184, distributed_optimizer:222,
save_persistables:236; DistributedOptimizer:240)."""

import abc

from .... import framework
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase


class Fleet(abc.ABC):
    def __init__(self):
        self._role_maker = None
        self._is_initialized = False
        self._optimizer = None

    def init(self, role_maker=None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker()
        role_maker.generate_role()
        self._role_maker = role_maker
        self._is_initialized = True

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_endpoints(self):
        return self._role_maker.get_trainer_endpoints()

    def server_endpoints(self):
        return self._role_maker.get_pserver_endpoints()

    def split_files(self, files):
        """Round-robin file shards per worker (reference fleet utility)."""
        idx = self.worker_index()
        n = self.worker_num()
        return files[idx::n]

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...

    @abc.abstractmethod
    def init_worker(self):
        ...

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        ...

    @abc.abstractmethod
    def run_server(self):
        ...

    @abc.abstractmethod
    def stop_worker(self):
        ...

    @abc.abstractmethod
    def save_persistables(self, executor, dirname, main_program=None):
        ...


class DistributedOptimizer(abc.ABC):
    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abc.abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ...
