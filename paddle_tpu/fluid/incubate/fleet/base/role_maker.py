"""Role makers: who am I in the job? (reference
``incubate/fleet/base/role_maker.py:25-497`` — MPI, PaddleCloud env,
UserDefined). TPU-native: roles come from env vars or jax.distributed;
worker = chip-owning process; server roles map to host-store shards."""

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "UserDefinedCollectiveRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []
        self._generated = False

    def generate_role(self):
        self._generated = True

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the reference's env-var contract: PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS, TRAINING_ROLE,
    PADDLE_PORT/PADDLE_PSERVERS (role_maker.py:327)."""

    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._generated:
            return
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        if role in ("TRAINER", "WORKER"):
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        else:
            self._role = Role.SERVER
            self._current_id = int(os.environ.get("PADDLE_PSERVER_ID", 0))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        pseps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                               os.environ.get("PADDLE_PSERVERS", ""))
        self._server_endpoints = [e for e in pseps.split(",") if e]
        self._generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = ["?"] * worker_num
        self._server_endpoints = server_endpoints or []


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints or ["127.0.0.1:6170"]
