"""Fleet utilities (reference ``incubate/fleet/utils/``)."""

from . import fleet_barrier_util, fleet_util, hdfs  # noqa: F401
from .fleet_util import FleetUtil  # noqa: F401
