"""Module-path parity for the reference's ``incubate/fleet/utils/hdfs.py``:
the hadoop-shell client lives in ``paddle_tpu.fs`` (one implementation for
the fluid and fleet entry points)."""

from .....fs import HDFSClient, LocalFS  # noqa: F401

__all__ = ["HDFSClient"]
