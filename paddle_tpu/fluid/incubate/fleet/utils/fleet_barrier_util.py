"""Filesystem-rendezvous trainer barrier.

Parity: reference ``fleet_barrier_util.py:20`` ``check_all_trainers_ready``
— each trainer uploads a ``ready.<run>.<epoch>.<rank>.done`` marker to a
shared directory and polls until every rank's marker for (run, epoch) is
present. The reference hardcodes HDFS; here any fs client with the
``is_dir/makedirs/upload/ls`` surface works (``paddle_tpu.fs.LocalFS``
for single-host / NFS jobs, ``HDFSClient`` for hadoop).

Two reference flaws are fixed rather than reproduced: the poll counts
markers of THIS (run, epoch) only (the reference's ``% trainer_num``
check aliases consecutive epochs), and the run id — from ``run_id`` or
``PADDLE_BARRIER_RUN_ID``, default the launch timestamp of rank 0's
env (``PADDLE_JOB_ID``) or ``"0"`` — keeps a RESTARTED job from
sailing through on the previous run's leftover markers. Jobs that
restart with the same run id must clear ``ready_path`` first.
"""

import os
import tempfile
import time

__all__ = ["check_all_trainers_ready"]


def check_all_trainers_ready(ready_path, epoch, fleet=None, fs_client=None,
                             run_id=None, timeout=600.0, interval=1.0):
    from ..collective import fleet as collective_fleet
    from .....fs import LocalFS

    fleet = fleet or collective_fleet
    client = fs_client or LocalFS()
    n, rank = fleet.worker_num(), fleet.worker_index()
    if run_id is None:
        run_id = os.environ.get("PADDLE_BARRIER_RUN_ID",
                                os.environ.get("PADDLE_JOB_ID", "0"))

    marker = "ready.%s.%s.%s.done" % (run_id, epoch, rank)
    fd, local = tempfile.mkstemp(prefix="barrier_marker_")
    os.close(fd)
    try:
        if not client.is_dir(ready_path):
            client.makedirs(ready_path)
        client.upload(local, os.path.join(ready_path, marker),
                      overwrite=True)
    finally:
        os.unlink(local)

    prefix = "ready.%s.%s." % (run_id, epoch)
    deadline = time.monotonic() + timeout
    while True:
        names = [os.path.basename(str(p)) for p in client.ls(ready_path)]
        ready = len([x for x in names if x.startswith(prefix)])
        if ready >= n:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                "barrier %r run %s epoch %s: %d/%d trainers ready after "
                "%.0fs" % (ready_path, run_id, epoch, ready, n, timeout))
        time.sleep(interval)
