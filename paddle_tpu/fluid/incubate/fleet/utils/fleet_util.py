"""Fleet training utilities.

Parity: reference ``incubate/fleet/utils/fleet_util.py`` (``FleetUtil:36``)
— the production-pipeline helper bundle (rank-gated logging, metric
aggregation over the AUC op's stat buckets, day/pass model directory
management with donefiles, online-pass scheduling). The reference's
xbox/pslib donefile variants and MPI allreduce are Baidu-infra specific;
here metric buckets are already global under the GSPMD collective modes
(stats live in replicated scope vars), and a ``reducer`` hook covers
per-process PS deployments.
"""

import logging
import os

import numpy as np

__all__ = ["FleetUtil"]

_logger = logging.getLogger(__name__)


class FleetUtil(object):
    def __init__(self, fleet=None):
        # default to the collective-mode fleet singleton, matching the
        # reference's module-level binding
        if fleet is None:
            from ..collective import fleet as collective_fleet

            fleet = collective_fleet
        self._fleet = fleet

    # -- rank-gated logging (reference :49,:69,:88) --------------------------
    def _is_rank0(self):
        try:
            return self._fleet.worker_index() == 0
        except Exception:
            return True

    def rank0_print(self, s):
        if self._is_rank0():
            print(s)

    def rank0_info(self, s):
        if self._is_rank0():
            _logger.info(s)

    def rank0_error(self, s):
        if self._is_rank0():
            _logger.error(s)

    # -- scope helpers (reference :107) --------------------------------------
    def set_zero(self, var_name, scope=None, place=None, param_type="int64"):
        """Zero a scope variable in place (e.g. AUC stat buckets between
        passes). ``place`` is accepted for API parity."""
        import paddle_tpu.fluid as fluid

        scope = scope or fluid.global_scope()
        cur = scope.find_var(var_name)
        if cur is None:
            raise KeyError("set_zero: no var %r in scope" % var_name)
        scope.set_var(var_name,
                      np.zeros(np.asarray(cur).shape, dtype=param_type))

    # -- global AUC from the auc op's stat buckets (reference :172) ----------
    def get_global_auc(self, scope=None, stat_pos=None, stat_neg=None,
                       reducer=None):
        """AUC from the accumulated pos/neg threshold buckets.

        With no bucket names given, the scope is searched for the single
        ``*.stat_pos``/``*.stat_neg`` pair ``layers.auc`` generates
        (programs with several auc ops must name the pair explicitly).
        Under the GSPMD collective modes the buckets in the scope are
        already global; in a per-process deployment pass ``reducer``
        (array -> summed array across workers) to aggregate first.
        Returns None when the buckets are absent (reference behavior).
        """
        import paddle_tpu.fluid as fluid

        scope = scope or fluid.global_scope()
        if stat_pos is not None and stat_neg is None and \
                stat_pos.endswith(".stat_pos"):
            stat_neg = stat_pos[:-len(".stat_pos")] + ".stat_neg"
        elif stat_neg is not None and stat_pos is None and \
                stat_neg.endswith(".stat_neg"):
            stat_pos = stat_neg[:-len(".stat_neg")] + ".stat_pos"
        if stat_pos is None or stat_neg is None:
            pos_names = [n for n in scope.var_names()
                         if n.endswith(".stat_pos")]
            if len(pos_names) != 1:
                self.rank0_print("not found auc bucket")
                return None
            stat_pos = pos_names[0]
            stat_neg = stat_pos[:-len(".stat_pos")] + ".stat_neg"
        pos_v = scope.find_var(stat_pos)
        neg_v = scope.find_var(stat_neg)
        if pos_v is None or neg_v is None:
            self.rank0_print("not found auc bucket")
            return None
        pos = np.asarray(pos_v, np.float64).reshape(-1)
        neg = np.asarray(neg_v, np.float64).reshape(-1)
        if reducer is not None:
            pos, neg = np.asarray(reducer(pos)), np.asarray(reducer(neg))
        # walk buckets from the highest threshold down (vectorized form of
        # the reference's trapezoid accumulation)
        pos_c = np.cumsum(pos[::-1])
        neg_c = np.cumsum(neg[::-1])
        pos_prev = np.concatenate([[0.0], pos_c[:-1]])
        neg_prev = np.concatenate([[0.0], neg_c[:-1]])
        area = np.sum((neg_c - neg_prev) * (pos_prev + pos_c) / 2.0)
        tot_pos, tot_neg = pos_c[-1], neg_c[-1]
        if tot_pos * tot_neg == 0:
            return 0.5
        return float(area / (tot_pos * tot_neg))

    def print_global_auc(self, scope=None, stat_pos=None, stat_neg=None,
                         print_prefix="", reducer=None):
        auc = self.get_global_auc(scope, stat_pos, stat_neg,
                                  reducer=reducer)
        self.rank0_print("%s global auc = %s" % (print_prefix, auc))
        return auc

    # -- day/pass model management (reference :348,:631,:656,:1144) ----------
    @staticmethod
    def _model_dir(output_path, day, pass_id):
        day = str(day)
        if pass_id in (None, -1, "-1"):
            return os.path.join(output_path, day, "base")
        return os.path.join(output_path, day, "delta-%s" % pass_id)

    def save_model(self, output_path, day, pass_id, executor, program,
                   feeded_var_names=None, target_vars=None):
        """Persist the program under the reference's
        ``<output>/<day>/delta-<pass>`` layout (``base`` for pass -1) and
        stamp the donefile rank-0-only. With ``feeded_var_names`` +
        ``target_vars`` the save is an inference-model export (pruned to
        the targets, reference save_paddle_inference_model:862);
        otherwise the full training persistables are written."""
        import paddle_tpu.fluid as fluid

        d = self._model_dir(output_path, day, pass_id)
        os.makedirs(d, exist_ok=True)
        if feeded_var_names is not None and target_vars is not None:
            fluid.io.save_inference_model(d, feeded_var_names, target_vars,
                                          executor, main_program=program)
        else:
            fluid.io.save_persistables(executor, d, program)
        if self._is_rank0():
            self.write_model_donefile(output_path, day, pass_id, d)
        return d

    def load_model(self, output_path, day, pass_id, executor, program):
        import paddle_tpu.fluid as fluid

        d = self._model_dir(output_path, day, pass_id)
        fluid.io.load_persistables(executor, d, program)
        return d

    def write_model_donefile(self, output_path, day, pass_id, model_dir,
                             donefile_name="donefile.txt"):
        line = "%s\t%s\t%s\n" % (day, pass_id, model_dir)
        with open(os.path.join(output_path, donefile_name), "a") as f:
            f.write(line)

    def get_last_save_model(self, output_path,
                            donefile_name="donefile.txt"):
        """(day, pass_id, model_dir) of the newest donefile entry, or
        (None, None, None)."""
        path = os.path.join(output_path, donefile_name)
        if not os.path.exists(path):
            return None, None, None
        lines = [l for l in open(path).read().splitlines() if l.strip()]
        if not lines:
            return None, None, None
        day, pass_id, model_dir = lines[-1].split("\t")
        return day, pass_id, model_dir

    # -- online pass scheduling (reference :1193) ----------------------------
    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass, is_data_hourly_placed):
        """Partition a day into passes of data splits. ``days``/``hours``
        accept explicit lists or the reference's brace-expansion strings
        (expanded in-process, not via a shell)."""
        hours = self._expand(hours)
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left, right = int(hours[0]), int(hours[-1])

        start = 0
        split_path = []
        for _ in range(splits_per_day):
            h, m = start // 60, start % 60
            if left <= h <= right:
                split_path.append("%02d" % h if is_data_hourly_placed
                                  else "%02d%02d" % (h, m))
            start += split_interval

        out, start = [], 0
        for _ in range(pass_per_day):
            chunk = split_path[start:start + split_per_pass]
            if chunk:
                out.append(chunk)
            start += split_per_pass
        return out

    @staticmethod
    def _expand(spec):
        """['a','b'] stays; "{0..23}" or "{a..b}" style expands like the
        shell brace range the reference popens."""
        if isinstance(spec, (list, tuple)):
            return [str(s) for s in spec]
        s = str(spec).strip()
        if s.startswith("{") and s.endswith("}") and ".." in s:
            lo, hi = s[1:-1].split("..")
            width = len(lo) if lo.startswith("0") and len(lo) > 1 else 0
            return [("%0*d" % (width, v)) if width else str(v)
                    for v in range(int(lo), int(hi) + 1)]
        return s.split()
