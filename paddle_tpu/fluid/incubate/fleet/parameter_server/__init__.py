"""Fleet parameter-server mode (reference
``incubate/fleet/parameter_server/distribute_transpiler/__init__.py``):
the ``fleet.init / distributed_optimizer / init_server / run_server /
init_worker`` recipe over the DistributeTranspiler + TCP serving tier.

Worker flow:
    fleet.init(role)                       # role: worker
    opt = fleet.distributed_optimizer(optimizer.SGD(...))
    opt.minimize(loss)                     # builds + transpiles
    fleet.init_worker()                    # swap tables to remote proxies
    exe.run(fleet.main_program, ...)
Server flow (servers build the SAME graph so the transpiler learns the
table shapes — the reference's pserver scripts do the same):
    fleet.init(role)                       # role: server
    opt = fleet.distributed_optimizer(optimizer.SGD(...))
    opt.minimize(loss)
    fleet.init_server()
    fleet.run_server()                     # blocks, serving this endpoint
"""

from ..base.fleet_base import DistributedOptimizer, Fleet
from ..base.role_maker import Role

__all__ = ["fleet", "TranspilerOptimizer", "ParameterServerFleet"]


class ParameterServerFleet(Fleet):
    def __init__(self):
        super().__init__()
        self._transpiler = None
        self._main_program = None
        self._startup_program = None
        self._pserver_prog = None

    # -- programs -----------------------------------------------------------
    @property
    def main_program(self):
        return self._main_program

    @property
    def startup_program(self):
        return self._startup_program

    # -- transpile hook (called by TranspilerOptimizer.minimize) ------------
    def _compile_time_transpile(self, loss, startup_program=None):
        from ....framework import default_startup_program
        from ....transpiler import DistributeTranspiler

        self._main_program = loss.block.program
        self._startup_program = (startup_program or
                                 default_startup_program())
        eps = ",".join(self._role_maker.get_pserver_endpoints())
        self._transpiler = DistributeTranspiler()
        self._transpiler.transpile(
            trainer_id=self._role_maker.worker_index(),
            program=self._main_program, pservers=eps,
            trainers=self._role_maker.worker_num())

    def _require_transpiled(self, what):
        if self._transpiler is None:
            raise RuntimeError(
                "%s needs fleet.distributed_optimizer(...).minimize(loss) "
                "first (nothing transpiled yet)" % what)

    # -- worker -------------------------------------------------------------
    def init_worker(self):
        self._require_transpiled("init_worker")
        self._main_program = self._transpiler.get_trainer_program()
        return self._main_program

    def stop_worker(self):
        from .....distributed import ps

        for name in list(self._transpiler._tables
                         if self._transpiler else []):
            table = ps.get_table(name)
            if hasattr(table, "close"):
                table.close()

    # -- server -------------------------------------------------------------
    def init_server(self, *args, **kwargs):
        self._require_transpiled("init_server")
        ep = self._role_maker.get_pserver_endpoints()[
            self._role_maker.server_index()]
        self._pserver_prog = self._transpiler.get_pserver_program(ep)
        return self._pserver_prog

    def run_server(self):
        """Blocks serving this endpoint (reference RunSyncLoop)."""
        if self._pserver_prog is None:
            self.init_server()
        from ....executor import Executor

        Executor().run(self._pserver_prog)

    # -- facade plumbing ----------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        return TranspilerOptimizer(optimizer, strategy, fleet_obj=self)

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io

        io.save_persistables(executor, dirname,
                             main_program or self._main_program)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None):
        from .... import io

        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                executor,
                                main_program or self._main_program)


class TranspilerOptimizer(DistributedOptimizer):
    """minimize() = inner optimizer minimize + PS transpile (reference
    ``TranspilerOptimizer.minimize``)."""

    def __init__(self, optimizer, strategy=None, fleet_obj=None):
        super().__init__(optimizer, strategy)
        self._fleet = fleet_obj

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        self._fleet._compile_time_transpile(loss, startup_program)
        return result


fleet = ParameterServerFleet()
