from . import base, collective, parameter_server, utils  # noqa: F401
