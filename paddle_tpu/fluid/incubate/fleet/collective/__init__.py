"""Fleet collective mode (reference ``incubate/fleet/collective/__init__.py``:
DistributedStrategy:134, CollectiveOptimizer:182, fleet singleton).

TPU-native execution: after ``fleet.distributed_optimizer(opt).minimize``,
the program carries explicit c_allreduce ops (GradAllReduce transpile) and
``fleet.main_program`` runs under shard_map on the device mesh
(``CompiledProgram.with_explicit_collectives``) — psum over ICI replaces the
NCCL ring. Multi-host: jax.distributed coordinates; the mesh spans hosts
(DCN between slices handled by XLA's collective hierarchy)."""

from .... import framework
from ....compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from ....transpiler.collective import GradAllReduce, LocalSGD
from ..base.fleet_base import DistributedOptimizer, Fleet
from ..base.role_maker import PaddleCloudRoleMaker

__all__ = ["fleet", "Collective", "DistributedStrategy", "CollectiveOptimizer"]


class DistributedStrategy:
    """Reference ``collective/__init__.py:134``."""

    def __init__(self):
        self.mode = "grad_allreduce"  # or "local_sgd"
        self.collective_mode = "grad_allreduce"
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.use_local_sgd = False
        self.local_sgd_k_steps = 1
        self.fuse_all_reduce_ops = True
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scale = 2.0 ** 15
        self.exec_strategy = ExecutionStrategy()
        self.build_strategy = BuildStrategy()


class Collective(Fleet):
    def __init__(self):
        super().__init__()
        self._origin_program = None
        self.main_program = None
        self.startup_program = None
        self._compiled = None

    def init(self, role_maker=None):
        super().init(role_maker)
        # multi-process jobs join the coordination service NOW so every
        # later mesh sees the global device view (reference: comm init at
        # fleet.init via c_gen_nccl_id RPC)
        from .....distributed import env as dist_env

        _, world, _ = dist_env.parallel_env()
        if world > 1:
            dist_env.init_parallel_env()

    def init_worker(self):
        from .....distributed import env as dist_env

        dist_env.init_parallel_env()

    def init_server(self, model_dir=None):
        raise NotImplementedError(
            "collective mode has no servers (reference parity)")

    def run_server(self):
        raise NotImplementedError(
            "collective mode has no servers (reference parity)")

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        return self._optimizer

    def compiled_program(self, loss_name=None):
        """The runnable artifact: shard_map over the device mesh."""
        if self._compiled is None:
            self._compiled = CompiledProgram(
                self.main_program
            ).with_explicit_collectives(loss_name=loss_name)
        return self._compiled

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io

        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                executor, main_program or self._origin_program)

    def save_persistables(self, executor, dirname, main_program=None,
                          filename=None):
        from .... import io

        io.save_persistables(executor, dirname,
                             main_program or self._origin_program, filename)


fleet = Collective()


class CollectiveOptimizer(DistributedOptimizer):
    """Reference ``collective/__init__.py:182``."""

    def __init__(self, optimizer, strategy=None):
        super().__init__(optimizer, strategy or DistributedStrategy())

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program, parameter_list,
                                        no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        opt = self._optimizer
        strategy = self._strategy
        if strategy.forward_recompute:
            from ....optimizer import RecomputeOptimizer

            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(strategy.recompute_checkpoints)
        if strategy.use_amp:
            from ....contrib.mixed_precision import decorate

            opt = decorate(opt, init_loss_scaling=strategy.amp_loss_scale,
                           use_dynamic_loss_scaling=True)

        main_program = loss.block.program
        startup_program = startup_program or framework.default_startup_program()
        fleet._origin_program = main_program
        optimize_ops, params_grads = opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        import jax

        nranks = max(fleet.worker_num(), 1)
        if nranks == 1:
            # single process: world = local device mesh
            nranks = len(jax.devices())
        if strategy.use_local_sgd:
            t = LocalSGD(nranks=nranks, k_steps=strategy.local_sgd_k_steps)
        else:
            t = GradAllReduce(nranks=nranks)
        t.transpile(startup_program, main_program,
                    rank=fleet.worker_index(),
                    endpoints=fleet.worker_endpoints() or None)
        fleet.main_program = main_program
        fleet.startup_program = startup_program
        return optimize_ops, params_grads
