"""append_backward / gradients — the autodiff entry points.

Parity: reference ``python/paddle/fluid/backward.py`` (``append_backward:933``,
``calc_gradient:1199``). TPU-first: instead of synthesizing per-op ``*_grad``
ops via C++ grad makers (``core.get_grad_op_desc``), one ``autodiff`` op is
appended whose lowering differentiates the traced forward with ``jax.grad``
(see ``ops/autodiff.py``). Duplicate-grad summation, stop_gradient, and
recompute fall out of the functional transform for free.
"""

from . import framework
from .framework import Parameter, Variable, grad_var_name

__all__ = ["append_backward", "gradients", "calc_gradient"]


def _collect_params(program, parameter_list=None, no_grad_set=None):
    no_grad = set(no_grad_set or [])
    if parameter_list is not None:
        names = [p.name if isinstance(p, Variable) else p for p in parameter_list]
        params = [program.global_block().var(n) for n in names]
    else:
        params = program.all_parameters()
    return [
        p for p in params
        if getattr(p, "trainable", True) and not p.stop_gradient and p.name not in no_grad
    ]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Appends gradient computation for ``loss`` w.r.t. trainable params.

    Returns ``[(param, grad_var), ...]`` like the reference. ``checkpoints``
    (recompute) is honored by ``jax.checkpoint`` over segments — see
    ``RecomputeOptimizer``.
    """
    program = loss.block.program
    block = loss.block
    params = _collect_params(program, parameter_list, no_grad_set)
    has_dist = any(op.type == "distributed_lookup_table" for op in block.ops)
    if not params and not has_dist:
        raise ValueError("No trainable parameters to differentiate")

    # Params consumed ONLY by one sparse lookup op get SelectedRows
    # gradients (reference lookup_table_op.cc grad kernel + selected_rows.h):
    # rows = the looked-up ids (cache slots for the host tier), values =
    # per-lookup cotangents. The autodiff lowering emits the pair without
    # ever materializing the dense grad. Sparse-eligible op types come from
    # the embedding engine (the one sparse-lookup entry point): legacy
    # lookup_table with is_sparse=True plus the engine's embedding_lookup /
    # host_embedding_lookup.
    # Two passes, order-independent: first collect every sparse lookup
    # param, then demote any param with another use ANYWHERE in the block
    # (input or output of any other op, or a dense lookup) — a single
    # program-order pass would miss consumers appearing before the lookup.
    from ..embedding.lookup import is_sparse_lookup

    sparse_params = {}
    for op in block.ops:
        if is_sparse_lookup(op):
            for w in op.input("W"):
                sparse_params.setdefault(w, []).append(op)
    for op in block.ops:
        sparse_w = set(op.input("W")) if is_sparse_lookup(op) else set()
        for name in list(op.input_arg_names()) + list(op.output_arg_names()):
            if name in sparse_params and name not in sparse_w:
                sparse_params[name] = None  # other use seen -> dense grad
    sparse_params = {k: v for k, v in sparse_params.items()
                     if v and len(v) == 1}

    grad_vars = []
    wrt, gnames = [], []
    sparse_wrt = []
    for p in params:
        gname = grad_var_name(p.name)
        if p.name in sparse_params:
            lookup = sparse_params[p.name][0]
            gv = block.create_var(name=gname, shape=(-1,) + tuple(p.shape[1:]),
                                  dtype=p.dtype, persistable=False,
                                  stop_gradient=True, type="selected_rows")
            block.create_var(name=gname + "@ROWS", shape=(-1,), dtype="int32",
                             persistable=False, stop_gradient=True)
            sparse_wrt.append(
                [p.name, lookup.input("Ids")[0], lookup.output("Out")[0]])
        else:
            gv = block.create_var(name=gname, shape=p.shape, dtype=p.dtype,
                                  persistable=False, stop_gradient=True)
        grad_vars.append(gv)
        wrt.append(p.name)
        gnames.append(gname)
        program.param_grad_map[p.name] = gname

    # loss@GRAD exists for API parity (constant 1 — scale handled in lowering)
    loss_grad = block.create_var(name=grad_var_name(loss.name), shape=loss.shape,
                                 dtype=loss.dtype, stop_gradient=True)

    attrs = {"loss": loss.name, "wrt": wrt, "grad_names": gnames, "loss_scale": 1.0}
    if sparse_wrt:
        attrs["sparse_wrt"] = sparse_wrt
    # host-table lookups (PS tier): the autodiff lowering binds the output
    # cotangent to <out>@PS_GRAD/@PS_ROWS; a distributed_push op appended
    # AFTER autodiff ships it to the host store (an explicit op so AMP can
    # unscale/overflow-gate the payload first) — no device grad var
    dist_push = []
    for op in block.ops:
        if op.type == "distributed_lookup_table":
            dist_push.append([op.attr("table_name"), op.input("Ids")[0],
                              op.output("Out")[0],
                              float(op.attr("lr", 0.01)),
                              op.attr("optimizer", "sgd")])
    if dist_push:
        attrs["dist_push"] = dist_push
    if checkpoints:
        attrs["checkpoints"] = [
            c.name if isinstance(c, Variable) else c for c in checkpoints
        ]
    block.append_op(
        "autodiff",
        inputs={"Loss": [loss]},
        outputs={"Grads": gnames},
        attrs=attrs,
    )
    for tname, _ids, out_name, lr, optname in dist_push:
        vname, rname = out_name + "@PS_GRAD", out_name + "@PS_ROWS"
        block.create_var(name=vname, shape=(-1, -1), dtype="float32",
                         stop_gradient=True)
        block.create_var(name=rname, shape=(-1,), dtype="int32",
                         stop_gradient=True)
        block.append_op(
            "distributed_push",
            inputs={"Values": [vname], "Rows": [rname]},
            attrs={"table_name": tname, "lr": lr, "optimizer": optname},
        )
    return list(zip(params, grad_vars))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference ``fluid.gradients`` / ``calc_gradient``."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    block = targets[0].block
    program = block.program
    gvars = []
    gnames = []
    for x in inputs:
        gname = grad_var_name(x.name)
        gv = block.create_var(name=gname, shape=x.shape, dtype=x.dtype,
                              stop_gradient=True)
        gvars.append(gv)
        gnames.append(gname)
    tg_names = []
    if target_gradients:
        tg_names = [
            tg.name if isinstance(tg, Variable) else tg for tg in target_gradients
        ]
    block.append_op(
        "calc_gradient",
        inputs={"Targets": [t.name for t in targets]},
        outputs={"Grads": gnames},
        attrs={
            "targets": [t.name for t in targets],
            "wrt": [x.name for x in inputs],
            "grad_names": gnames,
            "target_gradients": tg_names,
        },
    )
    return gvars


calc_gradient = gradients
