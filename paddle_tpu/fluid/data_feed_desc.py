"""DataFeedDesc — describes multislot training-data format (reference
``python/paddle/fluid/data_feed_desc.py:21``): a textproto of
``name``/``batch_size``/``pipe_command`` plus ``multi_slot_desc.slots``
entries, consumed by the Dataset engine's native parser. The restricted
grammar is parsed directly (no protobuf codegen — the reference's
``data_feed.proto`` fields are scalar + one repeated message)."""

import re

__all__ = ["DataFeedDesc"]

_SCALAR = re.compile(r'^\s*(\w+)\s*:\s*(?:"([^"]*)"|(\S+))\s*$')


class _Slot:
    def __init__(self):
        self.name = ""
        self.type = "uint64"
        self.is_dense = False
        self.is_used = False

    def text(self, indent="    "):
        return (
            "%sslots {\n"
            '%s    name: "%s"\n'
            '%s    type: "%s"\n'
            "%s    is_dense: %s\n"
            "%s    is_used: %s\n"
            "%s}\n"
        ) % (indent, indent, self.name, indent, self.type, indent,
             str(self.is_dense).lower(), indent, str(self.is_used).lower(),
             indent)


class DataFeedDesc:
    """Parse ``proto_file`` (MultiSlotDataFeed textproto) and expose the
    reference's mutators; ``desc()`` re-emits the textproto the Dataset
    engine consumes."""

    def __init__(self, proto_file):
        self.name = ""
        self.batch_size = 1
        self.pipe_command = "cat"
        self.slots = []
        self._extra = {}        # unhandled top-level scalars, preserved
        with open(proto_file) as f:
            self._parse(f.read())
        self._index = {s.name: i for i, s in enumerate(self.slots)}

    def _parse(self, text):
        stack = []      # nesting: "multi_slot_desc" / "slots"
        cur_slot = None
        for raw in text.splitlines():
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line.endswith("{"):
                key = line[:-1].strip()
                stack.append(key)
                if key == "slots":
                    cur_slot = _Slot()
                    self.slots.append(cur_slot)
                continue
            if line == "}":
                if stack and stack.pop() == "slots":
                    cur_slot = None
                continue
            m = _SCALAR.match(line)
            if not m:
                raise ValueError("unparseable DataFeedDesc line: %r" % raw)
            key, sval, bare = m.group(1), m.group(2), m.group(3)
            val = sval if sval is not None else bare
            if cur_slot is not None:
                if key in ("is_dense", "is_used"):
                    setattr(cur_slot, key, val.lower() == "true")
                elif key in ("name", "type"):
                    setattr(cur_slot, key, val)
            elif key == "batch_size":
                self.batch_size = int(val)
            elif key in ("name", "pipe_command"):
                setattr(self, key, val)
            else:
                # preserve unhandled fields (thread_num, fs_name, ...)
                # verbatim so a parse -> desc() round trip is lossless
                self._extra[key] = raw.strip()

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def _each(self, names, fn):
        for n in names:
            if n not in self._index:
                raise ValueError(
                    "slot %r not found in the DataFeedDesc (have %s)"
                    % (n, sorted(self._index)))
            fn(self.slots[self._index[n]])

    def set_dense_slots(self, dense_slots_name):
        """Mark slots dense (fed as plain Tensors); all slots default
        sparse, like the reference."""
        self._each(dense_slots_name,
                   lambda s: setattr(s, "is_dense", True))

    def set_use_slots(self, use_slots_name):
        """Mark slots used — only used slots are fed to the program."""
        self._each(use_slots_name, lambda s: setattr(s, "is_used", True))

    def desc(self):
        out = ['name: "%s"' % self.name,
               "batch_size: %d" % self.batch_size,
               'pipe_command: "%s"' % self.pipe_command]
        out.extend(self._extra.values())
        out.append("multi_slot_desc {")
        for s in self.slots:
            out.append(s.text().rstrip("\n"))
        out.append("}")
        return "\n".join(out) + "\n"
