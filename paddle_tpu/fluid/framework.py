"""Core graph-building IR: Program / Block / Operator / Variable / Parameter.

Capability parity with the reference's ``python/paddle/fluid/framework.py``
(Variable:561, Operator:1680, Block:2132, Program:3515) and the C++ desc
layer (``paddle/fluid/framework/program_desc.h:30``), re-designed TPU-first:

* The IR is a declarative program of named ops over named vars — the same
  exchange-format role ``ProgramDesc`` plays — but there is no per-op C++
  kernel dispatch. Whole blocks are lowered to a single pure JAX function
  and compiled by XLA (see ``executor.py``).
* Shape inference runs through ``jax.eval_shape`` on each op's lowering rule
  (single source of truth), instead of hand-written InferShape per op.
* Serialization is protobuf-backed (``core/framework_pb2``), mirroring the
  reference's on-disk capability.
"""

import contextlib
import copy
import itertools

import numpy as np

from . import unique_name


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """Reference ``fluid.name_scope``: cosmetic op grouping recorded as
    the ``op_namescope`` attr (what the reference's graph viewer
    groups by); no effect on execution."""
    if prefix:
        _name_scope_stack.append(str(prefix))
    try:
        yield
    finally:
        if prefix:
            _name_scope_stack.pop()


def _program_version():
    from .compat import PROGRAM_VERSION

    return PROGRAM_VERSION

# ---------------------------------------------------------------------------
# dtype handling
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "float32": np.dtype("float32"),
    "float64": np.dtype("float64"),
    "float16": np.dtype("float16"),
    "bfloat16": None,  # filled lazily to avoid importing jax at module load
    "int8": np.dtype("int8"),
    "uint8": np.dtype("uint8"),
    "int16": np.dtype("int16"),
    "int32": np.dtype("int32"),
    "int64": np.dtype("int64"),
    "bool": np.dtype("bool"),
}


def convert_dtype(dtype):
    """Normalize a dtype spec (str / np.dtype / jnp dtype) to np.dtype."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        if dtype in _DTYPE_ALIASES and _DTYPE_ALIASES[dtype] is not None:
            return _DTYPE_ALIASES[dtype]
    return np.dtype(dtype)


def dtype_str(dtype):
    return np.dtype(dtype).name


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------


class Variable:
    """A named tensor slot in a Block (reference ``framework.py:561``).

    Holds static metadata only; at run time the value lives in a Scope as a
    device-resident ``jax.Array``. ``shape`` may contain -1 for deferred
    (batch) dimensions.
    """

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype="float32",
        persistable=False,
        stop_gradient=False,
        is_data=False,
        initializer=None,
        type="lod_tensor",
        lod_level=0,
    ):
        self.block = block
        self.name = name or unique_name.generate("_generated_var")
        self.shape = tuple(shape) if shape is not None else ()
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level
        # "lod_tensor" (dense) or "selected_rows" (sparse rows+values pair;
        # a selected_rows var NAME binds the values array in the env and
        # NAME + "@ROWS" binds the int32 row-index array — the TPU-native
        # encoding of reference SelectedRows, selected_rows.h:32)
        self.type = type
        self.op = None  # producing op, set by append_op

    # -- python operator sugar (maps to ops, usable while building graphs) --
    def _binary(self, other, op_type, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary_op(self, other, op_type, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __radd__(self, other):
        return self._binary(other, "elementwise_add", True)

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __rmul__(self, other):
        return self._binary(other, "elementwise_mul", True)

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __neg__(self):
        from .layers.nn import scale as _scale

        return _scale(self, scale=-1.0)

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")

    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers.tensor import cast

        return cast(self, dtype)

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            dtype_str(self.dtype),
            ", persistable" if self.persistable else "",
        )

    def to_desc(self):
        return {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": dtype_str(self.dtype),
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", False),
        }


class Parameter(Variable):
    """A trainable persistable Variable (reference ``framework.py:4459``)."""

    def __init__(self, block, shape, dtype, name=None, trainable=True,
                 regularizer=None, initializer=None, do_model_average=False,
                 learning_rate=1.0):
        super().__init__(
            block,
            name=name,
            shape=shape,
            dtype=dtype,
            persistable=True,
            stop_gradient=not trainable,
        )
        self.trainable = trainable
        self.regularizer = regularizer
        self.initializer = initializer
        self.do_model_average = do_model_average
        self.optimize_attr = {"learning_rate": learning_rate}


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name):
    return name + GRAD_SUFFIX


_PKG_DIR = None


def _user_callsite(max_frames=3):
    """File:line of the nearest frames OUTSIDE paddle_tpu — the user's layer
    call site (cheap: walks raw frames, no traceback formatting)."""
    global _PKG_DIR
    if _PKG_DIR is None:
        import os
        import sys  # noqa: F401

        _PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    import sys

    frames = []
    f = sys._getframe(2)
    while f is not None and len(frames) < max_frames:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR):
            frames.append("%s:%d in %s" % (fn, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return frames


def record_op_callstacks(enabled=True):
    """Toggle op call-site recording (on by default; tiny per-op cost at
    graph-build time only)."""
    Operator._record_callstacks = bool(enabled)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


class Operator:
    """One IR op: type + named input/output var lists + attrs.

    Mirrors the reference ``OpDesc`` (``framework.proto:43``); execution-time
    semantics come from the op registry's lowering rule (``registry.py``).
    """

    # op-attributed errors (reference framework/op_call_stack.cc): each op
    # records where user code created it, so lowering/runtime failures can
    # name the layer call site. Toggle via record_op_callstacks().
    _record_callstacks = True

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        # canonical form: {slot: [var_name, ...]}
        self.inputs = {k: _as_name_list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: _as_name_list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        self.callstack = _user_callsite() if Operator._record_callstacks \
            else None

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        return "{%s: (%s) -> (%s)}" % (
            self.type,
            ", ".join("%s=%s" % kv for kv in self.inputs.items()),
            ", ".join("%s=%s" % kv for kv in self.outputs.items()),
        )

    def to_desc(self):
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": _sanitize_attrs(self.attrs),
        }


def _as_name_list(v):
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [_as_name(x) for x in v]
    return [_as_name(v)]


def _as_name(v):
    return v.name if isinstance(v, Variable) else str(v)


def _sanitize_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, np.generic):
            out[k] = v.item()
        elif isinstance(v, Variable):
            out[k] = v.name
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


class Block:
    """An ordered op list + var table (reference ``framework.py:2132``)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("Variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        self.program._bump()
        return v

    def create_parameter(self, **kwargs):
        p = Parameter(self, **kwargs)
        # parameters live in the enclosing (global) block's var table
        gb = self.program.global_block()
        gb.vars[p.name] = p
        self.program._bump()
        return p

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        if _name_scope_stack:
            # cosmetic namespace for viz/debug tools (reference
            # op_desc "op_namescope"); ignored by every lowering
            op.attrs.setdefault("op_namescope",
                                "/".join(_name_scope_stack))
        self.ops.append(op)
        for name in op.output_arg_names():
            v = self._find_var_recursive(name)
            if v is not None and v.op is None:
                v.op = op
        self.program._bump()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump()
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def to_desc(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_desc() for v in self.vars.values()],
            "ops": [op.to_desc() for op in self.ops],
        }


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


class Program:
    """A multi-block IR program (reference ``framework.py:3515``).

    ``_mutation`` is a monotonically increasing edit counter used by the
    Executor's compile cache to detect graph changes cheaply.
    """

    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._mutation = 0
        self._seed_counter = 0
        # unique per-Program token: the Executor cache key must not use
        # id(program) — a GC'd Program's id can be reused and serve a stale
        # compiled step
        self._uid = next(Program._uid_counter)
        # set by append_backward: maps param name -> grad var name
        self.param_grad_map = {}

    def _bump(self):
        self._mutation += 1

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump()
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def clone(self, for_test=False):
        """Deep-copies the IR. ``for_test=True`` switches ops to eval mode
        (dropout off, batch_norm uses running stats) like the reference's
        ``Program.clone(for_test=True)``."""
        p = Program.__new__(Program)
        p._uid = next(Program._uid_counter)
        p.random_seed = self.random_seed
        p._mutation = 0
        p._seed_counter = self._seed_counter
        p.param_grad_map = dict(self.param_grad_map)
        p.current_block_idx = 0
        p.blocks = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for v in blk.vars.values():
                nv = copy.copy(v)
                nv.block = nb
                nv.op = None
                nb.vars[nv.name] = nv
            for op in blk.ops:
                attrs = dict(op.attrs)
                if for_test and attrs.get("is_test") is False:
                    attrs["is_test"] = True
                nb.ops.append(Operator(nb, op.type, op.inputs, op.outputs, attrs))
            p.blocks.append(nb)
        return p

    def _prune(self, targets):
        """Keeps only ops needed to compute ``targets`` (reference prune.h).

        Returns a cloned pruned Program. Persistable writes (optimizer
        updates) are dropped unless needed — this is what
        ``save_inference_model`` uses.

        Control-flow ops (while/cond/...) declare only part of their
        data flow as explicit inputs/outputs; the rest rides their
        sub-blocks (a branch reads a parent-block fc output, a while
        body writes an array the tail reads). Reverse reachability
        therefore matches against each op's TRANSITIVE reads/writes —
        explicit args plus every nested sub-block op's args (reference
        prune.h walks sub-block descs the same way). Sub-block-internal
        names never collide into block 0 (unique-name generation), so
        the widening only ever keeps more, never less.
        """
        target_names = set(_as_name_list(targets))
        p = self.clone(for_test=True)
        blk = p.global_block()

        def _transitive_args(op):
            reads = set(op.input_arg_names())
            writes = set(op.output_arg_names())
            seen, stack = set(), [op]
            while stack:
                for key, val in stack.pop().attrs.items():
                    if key == "sub_block" or key.endswith("_block"):
                        idxs = [val] if isinstance(val, int) else []
                    elif key == "blocks" and isinstance(val, (list, tuple)):
                        idxs = [v for v in val if isinstance(v, int)]
                    else:
                        continue
                    for idx in idxs:
                        if 0 <= idx < len(p.blocks) and idx not in seen:
                            seen.add(idx)
                            for sub_op in p.blocks[idx].ops:
                                reads.update(sub_op.input_arg_names())
                                writes.update(sub_op.output_arg_names())
                                stack.append(sub_op)
            return reads, writes

        needed = set(target_names)
        kept = []
        for op in reversed(blk.ops):
            reads, writes = _transitive_args(op)
            if writes & needed:
                kept.append(op)
                needed.update(reads)
        blk.ops = list(reversed(kept))
        return p

    # -- serialization ------------------------------------------------------
    def to_desc(self):
        return {
            "version": _program_version(),
            "random_seed": self.random_seed,
            "blocks": [b.to_desc() for b in self.blocks],
            "param_grad_map": dict(self.param_grad_map),
        }

    def serialize_to_string(self):
        from .core import proto_io

        return proto_io.program_to_bytes(self.to_desc())

    @staticmethod
    def parse_from_string(data):
        from .core import proto_io

        return Program.from_desc(proto_io.program_from_bytes(data))

    @staticmethod
    def from_desc(desc):
        p = Program.__new__(Program)
        p._uid = next(Program._uid_counter)
        p.random_seed = desc.get("random_seed", 0)
        p._mutation = 0
        p._seed_counter = 0
        p.param_grad_map = dict(desc.get("param_grad_map", {}))
        p.current_block_idx = 0
        p.blocks = []
        for bdesc in desc["blocks"]:
            blk = Block(p, bdesc["idx"], bdesc.get("parent_idx", -1))
            for vdesc in bdesc["vars"]:
                if vdesc.get("is_parameter"):
                    v = Parameter(
                        blk,
                        shape=vdesc["shape"],
                        dtype=vdesc["dtype"],
                        name=vdesc["name"],
                        trainable=vdesc.get("trainable", True),
                    )
                else:
                    v = Variable(
                        blk,
                        name=vdesc["name"],
                        shape=vdesc["shape"],
                        dtype=vdesc["dtype"],
                        persistable=vdesc.get("persistable", False),
                        stop_gradient=vdesc.get("stop_gradient", False),
                        is_data=vdesc.get("is_data", False),
                    )
                blk.vars[v.name] = v
            for odesc in bdesc["ops"]:
                blk.ops.append(
                    Operator(blk, odesc["type"], odesc["inputs"],
                             odesc["outputs"], odesc["attrs"])
                )
            p.blocks.append(blk)
        return p

    def __repr__(self):
        lines = []
        for blk in self.blocks:
            lines.append("block %d (parent %d):" % (blk.idx, blk.parent_idx))
            for op in blk.ops:
                lines.append("  " + repr(op))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# default programs / guards (reference framework.py:4559,4593)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program):
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


# -- dygraph mode switch (populated by dygraph package) ---------------------

_dygraph_tracer_ = None


def _dygraph_tracer():
    return _dygraph_tracer_


def in_dygraph_mode():
    return _dygraph_tracer_ is not None


@contextlib.contextmanager
def _dygraph_guard(tracer):
    global _dygraph_tracer_
    old = _dygraph_tracer_
    _dygraph_tracer_ = tracer
    try:
        yield
    finally:
        _dygraph_tracer_ = old
