"""Graph drawing entry points (reference ``python/paddle/fluid/net_drawer.py``
— graphviz export of a Program). Thin veneer over ``debugger``."""

from .debugger import draw_block_graphviz

__all__ = ["draw_graph", "draw_block_graphviz"]


def draw_graph(startup_program, main_program, path=None, block_idx=0,
               **kwargs):
    """Dot source for the main program's block (startup accepted for
    reference-signature parity; its initializer subgraph is omitted)."""
    return draw_block_graphviz(main_program.blocks[block_idx], path=path)
