"""Deterministic fault injection for the fault-tolerance test harness.

Production code is instrumented with NAMED injection points — a
one-line ``faults.check("io.write")`` at the spot where a real failure
would bite (between a checkpoint's tmp-file write and its rename, inside
the reader's staging thread, around a pserver RPC, once per training
step). Unarmed points cost a dict lookup and are no-ops; armed points
count their hits and fire deterministically on the Nth one, so a test
can reproduce "the worker died right after step 6's checkpoint" exactly.

Arming is programmatic (``faults.arm("worker.exit", after_n=5)``) or —
for subprocesses spawned by ``distributed.launch`` — environmental:
``PADDLE_FAULTS=point:after_n[:times],point2:after_n`` is parsed at
import. The injected exception defaults to ``FaultInjected``, a
``resilience.TransientError`` subclass, so points wrapped in a shared
``Retry`` demonstrably absorb it; pass ``exc=`` a different class to
model a non-retryable failure. ``worker.exit`` is special: instead of
raising it hard-kills the process with ``os._exit(EXIT_CODE)`` — the
crash the launcher's gang restart exists for.

Every fire is counted in ``monitor`` (``faults_injected_total`` by
point), so a test can assert the fault actually happened.
"""

import os
import threading

from . import monitor as _monitor
from .resilience import TransientError

__all__ = ["FaultInjected", "POINTS", "EXIT_CODE", "arm", "disarm",
           "reset", "is_armed", "hits", "check", "take"]

ENV = "PADDLE_FAULTS"
EXIT_CODE = 43  # distinguishable from python's 1 and signal deaths

# the instrumented sites (arming an unknown point is an error — a typo'd
# point name silently never firing is the worst failure mode of a fault
# harness)
POINTS = (
    "io.write",        # fluid/core/tensor_io.save_combine: after the tmp
                       #   file is written, BEFORE the atomic rename
    "reader.stage",    # fluid/reader.stage_feed: inside the DeviceStager
                       #   producer thread, before the device_put
    "ps.rpc",          # distributed/ps_server._Conn: before each framed
                       #   request round-trip
    "coord.rpc",       # distributed/coordination.CoordClient: before
                       #   each coordination-service round-trip
    "coord.crash",     # distributed/coordination.CoordServer: taken in
                       #   the serve loop — the server dies mid-request
                       #   (crash(): no final snapshot, WAL-only state)
    "coord.partition", # distributed/coordination._CoordConn: each armed
                       #   hit fails one client attempt transiently — a
                       #   network partition of exactly N attempts
    "worker.exit",     # training scripts call check() once per step;
                       #   fires os._exit(EXIT_CODE) — a hard crash
    "step.nonfinite",  # executor anomaly check: the step's results are
                       #   treated as non-finite (policy path exercised
                       #   without building a diverging model)
    "worker.preempt",  # training scripts call check() once per step;
                       #   fires SIGTERM at this process — the eviction
                       #   notice distributed.preemption drains on
    "worker.hang",     # training scripts call check() once per step;
                       #   sleeps $PADDLE_FAULT_HANG_SECONDS (default
                       #   3600) with heartbeats still beating — the
                       #   live-hang the step-deadline watchdog catches
)

ENV_HANG_SECONDS = "PADDLE_FAULT_HANG_SECONDS"


class FaultInjected(TransientError):
    """Default injected failure — transient, so retry layers absorb it."""


class _Fault:
    __slots__ = ("after_n", "times", "exc", "hits", "fired")

    def __init__(self, after_n, times, exc):
        self.after_n = int(after_n)
        self.times = int(times)
        self.exc = exc
        self.hits = 0
        self.fired = 0


_LOCK = threading.Lock()
_ARMED = {}

_M_INJECTED = {}


def _m_injected(point):
    m = _M_INJECTED.get(point)
    if m is None:
        m = _M_INJECTED[point] = _monitor.counter(
            "faults_injected_total",
            help="injected faults fired, by injection point",
            labels={"point": point})
    return m


def arm(point, after_n=0, times=1, exc=FaultInjected):
    """Arm ``point``: the first ``after_n`` hits pass through, then the
    next ``times`` hits fire (raise ``exc``, or ``os._exit`` for
    ``worker.exit``); later hits pass through again. Re-arming replaces
    the previous setting and resets counters."""
    if point not in POINTS:
        raise ValueError("unknown fault point %r; known: %s"
                         % (point, ", ".join(POINTS)))
    with _LOCK:
        _ARMED[point] = _Fault(after_n, times, exc)


def disarm(point):
    with _LOCK:
        _ARMED.pop(point, None)


def reset():
    """Disarm everything (test teardown)."""
    with _LOCK:
        _ARMED.clear()


def is_armed(point):
    return point in _ARMED


def hits(point):
    """Hit count since arming (0 if not armed)."""
    with _LOCK:
        f = _ARMED.get(point)
        return f.hits if f is not None else 0


def _fire(point):
    """Count a hit; True if this hit should fail."""
    with _LOCK:
        f = _ARMED.get(point)
        if f is None:
            return None
        f.hits += 1
        if f.hits > f.after_n and f.fired < f.times:
            f.fired += 1
            _m_injected(point).inc()
            return f.exc
    return None


def check(point):
    """The injection point: no-op unless armed and due. ``worker.exit``
    hard-exits the process, ``worker.preempt`` delivers a real SIGTERM
    to it, ``worker.hang`` wedges the calling thread; every other point
    raises the armed exception class (constructed with a descriptive
    message)."""
    exc = _fire(point)
    if exc is None:
        return
    if point == "worker.exit":
        os._exit(EXIT_CODE)  # simulated hard crash: no atexit, no cleanup — anything softer would not exercise the launcher's restart path
    if point == "worker.preempt":
        import signal

        os.kill(os.getpid(), signal.SIGTERM)
        return  # the drain handler decides what happens next
    if point == "worker.hang":
        import time

        # a live hang: this thread wedges but daemon threads (the
        # Heartbeat stamper) keep running, so the stamp stays fresh
        # while the step counter freezes — only the step-deadline
        # watchdog can catch it
        time.sleep(float(os.environ.get(ENV_HANG_SECONDS, "3600")
                         or 3600))
        return
    raise exc("injected fault at %r" % point)


def take(point):
    """Like ``check`` but RETURNS True instead of raising — for sites
    that inject a condition rather than an exception (the executor's
    ``step.nonfinite`` pretends the step produced NaNs)."""
    return _fire(point) is not None


def _parse_env(spec):
    """``point:after_n[:times]`` comma-separated; bad entries raise (a
    silently ignored fault spec would invalidate the test using it)."""
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                "%s entry %r: want point:after_n[:times]" % (ENV, entry))
        point, after_n = parts[0], int(parts[1])
        times = int(parts[2]) if len(parts) == 3 else 1
        out.append((point, after_n, times))
    return out


def arm_from_env(environ=None):
    """Arm points from ``PADDLE_FAULTS`` (called at import; exposed so
    tests can re-parse after monkeypatching the environment)."""
    spec = (environ if environ is not None else os.environ).get(ENV)
    if not spec:
        return
    for point, after_n, times in _parse_env(spec):
        arm(point, after_n=after_n, times=times)


arm_from_env()
