"""Persistent compilation cache: AOT-serialized executables on disk.

Every process restart, elastic gang reformation (``distributed.launch``
shrink-to-survivors) and serving cold-start used to re-trace and
re-compile every ``(program, signature, k)`` entry from scratch — the
direct multiplier on elastic-recovery downtime and serving warm-up.
This module gives the executor's in-memory compile cache a second,
on-disk tier built on JAX AOT: the first call of a freshly built step
either ``deserialize_and_load``s a previously serialized executable
(no trace, no XLA compile) or ``lower().compile()``s live, serializes
the result and saves it atomically (tmp+fsync+rename, the PR 4
checkpoint discipline) for the next process.

Keying: the executor's in-memory key leans on ``Program._uid`` — a
process-local monotonic token that means nothing to another process.
The disk key replaces it with a CONTENT hash: program-desc digest
(``Program.serialize_to_string``), feed signature, fetch/state names,
strategy fingerprint (mode + mesh axes/shape + donation setting),
``iters``, the anomaly-policy donation bit, and an environment
fingerprint (jax/jaxlib/XLA versions, platform, device kind, device
count). A stale entry — new jaxlib, different chip, edited program,
re-formed mesh — therefore MISSES cleanly instead of loading garbage.

Robustness contract: a corrupted, truncated or otherwise unloadable
entry is never fatal — it is quarantined (renamed aside, counted in
``compile_cache_quarantined_total``) and the step compiles live.
Concurrent processes sharing one cache dir are safe: reads see either
a complete entry or none (atomic rename), and the last writer wins.

Disabled (``PADDLE_COMPILE_CACHE_DIR`` unset) the module is inert:
``wrap_jit`` hands back the jit object unchanged, so behavior is
bit-identical to a build without this file.
"""

import contextlib
import hashlib
import logging
import os
import pickle
import threading
import time

from . import monitor as _monitor

__all__ = [
    "ENV_DIR", "ENV_MAX_BYTES", "ENTRY_SUFFIX", "PRELOWERED_DIRNAME",
    "cache_dir", "enabled", "active", "override_dir", "program_digest",
    "step_key", "entry_path", "wrap_jit", "prewarm", "disk_hit_count",
]

logger = logging.getLogger(__name__)

ENV_DIR = "PADDLE_COMPILE_CACHE_DIR"
ENV_MAX_BYTES = "PADDLE_COMPILE_CACHE_MAX_BYTES"
ENTRY_SUFFIX = ".xc"            # one serialized executable per file
QUARANTINE_SUFFIX = ".quarantined"
PRELOWERED_DIRNAME = "__prelowered__"   # model-adjacent read-only tier
# Bump on any incompatible change to the entry pickle layout — old
# entries then miss via the key hash AND fail the format check.
FORMAT_VERSION = 1

# -- monitor series -----------------------------------------------------------
_M_DISK_HIT = _monitor.counter(
    "executor_compile_cache_disk_hit_total",
    help="compiled steps served by deserializing an on-disk AOT "
         "executable (no trace, no XLA compile — the restart/cold-start "
         "fast path)")
_M_DISK_MISS = _monitor.counter(
    "executor_compile_cache_disk_miss_total",
    help="disk-tier lookups that found no loadable entry and compiled "
         "live (counted only when a cache dir is configured)")
# tier-labeled views of the executor's hit/miss series: dashboards keyed
# on the unlabeled legacy names keep working, tier={memory,disk} splits
# warm-process hits from restart hits (executor.py owns tier=memory)
_M_HIT_TIER_DISK = _monitor.counter(
    "executor_compile_cache_hit_total",
    help="compile-cache hits by tier",
    labels={"tier": "disk"})
_M_MISS_TIER_DISK = _monitor.counter(
    "executor_compile_cache_miss_total",
    help="compile-cache misses by tier",
    labels={"tier": "disk"})
_M_LOAD_SECONDS = _monitor.histogram(
    "compile_cache_load_seconds",
    help="wall time to read + deserialize_and_load one cache entry "
         "(what a restart pays INSTEAD of trace+compile)")
_M_SAVE_SECONDS = _monitor.histogram(
    "compile_cache_save_seconds",
    help="wall time to serialize + atomically write one cache entry "
         "(paid once per live compile when the cache is enabled)")
_M_QUARANTINED = _monitor.counter(
    "compile_cache_quarantined_total",
    help="corrupted/truncated/unloadable cache entries renamed aside "
         "(the run fell back to a live compile — never fatal)")
_M_EVICTED = _monitor.counter(
    "compile_cache_evicted_total",
    help="cache entries deleted by LRU-by-mtime eviction "
         "(PADDLE_COMPILE_CACHE_MAX_BYTES)")
_M_PREWARMED = _monitor.counter(
    "compile_cache_prewarmed_total",
    help="entries validated and paged in by compile_cache.prewarm "
         "(launcher pre-warm before rendezvous / restore_on_restart)")

_DIR_OVERRIDE = None


# -- configuration ------------------------------------------------------------
def cache_dir():
    """The read-write cache directory, or None when the cache is off.
    ``override_dir`` (the ``save_inference_model(prelower=True)`` path)
    beats the ``PADDLE_COMPILE_CACHE_DIR`` environment variable."""
    if _DIR_OVERRIDE is not None:
        return _DIR_OVERRIDE
    return os.environ.get(ENV_DIR) or None


def enabled():
    return cache_dir() is not None


def active(read_dirs=None):
    """True when any tier could serve or store an entry: the env/override
    write dir, or a read-only dir list (a Predictor's model-adjacent
    ``__prelowered__`` directory works without the env var)."""
    return enabled() or bool(read_dirs)


def max_cache_bytes():
    v = os.environ.get(ENV_MAX_BYTES)
    try:
        return int(v) if v else None
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", ENV_MAX_BYTES, v)
        return None


@contextlib.contextmanager
def override_dir(dirname):
    """Temporarily route the cache at ``dirname`` regardless of the
    environment — ``save_inference_model(prelower=True)`` uses this to
    drop executables next to the model."""
    global _DIR_OVERRIDE
    prev = _DIR_OVERRIDE
    _DIR_OVERRIDE = dirname
    try:
        yield
    finally:
        _DIR_OVERRIDE = prev


# -- keying -------------------------------------------------------------------
def _env_fingerprint():
    """Everything that invalidates a serialized executable without the
    program changing: jax/jaxlib/XLA versions, backend platform, chip
    kind, device count. Part of every key, so a foreign entry misses
    by filename instead of failing to load."""
    import jax

    try:
        import jaxlib

        jaxlib_ver = getattr(jaxlib, "__version__", "?")
    except ImportError:  # pragma: no cover - jaxlib always rides with jax
        jaxlib_ver = "?"
    xla_ver = getattr(getattr(jax, "lib", None), "xla_extension_version",
                      None)
    dev = jax.devices()[0]
    return (FORMAT_VERSION, jax.__version__, jaxlib_ver, xla_ver,
            dev.platform, getattr(dev, "device_kind", "?"),
            jax.device_count())


def program_digest(program):
    """Content hash of the program desc (structure + random_seed), cached
    per mutation counter so repeated key computations don't re-serialize
    the whole desc."""
    cached = getattr(program, "_compile_cache_digest", None)
    if cached is not None and cached[0] == program._mutation:
        return cached[1]
    digest = hashlib.sha256(program.serialize_to_string()).hexdigest()
    program._compile_cache_digest = (program._mutation, digest)
    return digest


def _strategy_fingerprint(strategy):
    if strategy is None:
        return None
    mesh = strategy.mesh
    bs = getattr(strategy, "_build_strategy", None)
    mb_vars = getattr(strategy, "_microbatch_vars", None)
    return (
        getattr(strategy, "_mode", "gspmd"),
        tuple(getattr(strategy, "_mesh_axes", ()) or ()),
        tuple(sorted(mesh.shape.items())) if mesh is not None else None,
        bool(getattr(bs, "enable_inplace", True)),
        getattr(strategy, "_loss_name", None),
        getattr(strategy, "_num_microbatches", None),
        tuple(sorted(mb_vars)) if mb_vars is not None else None,
    )


def step_key(program, feed_sig, fetch_names, state_names, strategy,
             iters, donate):
    """Disk key for one compiled step: the executor's in-memory tuple
    with the process-local ``Program._uid`` replaced by the content
    digest, plus the environment fingerprint. Returns a hex string
    (the entry's filename stem)."""
    parts = (
        _env_fingerprint(),
        program_digest(program),
        tuple(feed_sig),
        tuple(fetch_names),
        tuple(state_names),
        _strategy_fingerprint(strategy),
        int(iters),
        bool(donate),
    )
    return hashlib.sha256(repr(parts).encode("utf-8")).hexdigest()


def entry_path(dirname, key):
    return os.path.join(dirname, key + ENTRY_SUFFIX)


# -- entry I/O ----------------------------------------------------------------
def _quarantine(path):
    """Rename a bad entry aside (never delete: the bytes are evidence)
    so the next lookup misses instead of re-tripping on it."""
    try:
        os.replace(path, path + QUARANTINE_SUFFIX)
    except OSError:
        # a racing process already moved/removed it — equally gone
        pass
    _M_QUARANTINED.inc()


def _load_entry(path):
    """Deserialize one entry into a callable executable, or None
    (quarantining the entry) on ANY failure."""
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            blob = f.read()
        # the single sanctioned deserialization site for cache entries
        # (tools/check_resilience.py lints other pickle.load callers)
        entry = pickle.loads(blob)  # noqa: sanctioned-cache-read
        if not isinstance(entry, dict) or \
                entry.get("format") != FORMAT_VERSION:
            raise ValueError("unrecognized cache entry layout")
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        exe = deserialize_and_load(entry["payload"], entry["in_tree"],
                                   entry["out_tree"])
    except Exception as e:
        logger.warning("compile cache entry %s is unloadable (%s: %s); "
                       "quarantining and compiling live",
                       path, type(e).__name__, e)
        _quarantine(path)
        return None
    _M_LOAD_SECONDS.observe(time.perf_counter() - t0)
    try:
        # LRU-by-mtime: a hit is a use
        os.utime(path, None)
    except OSError:
        pass
    return exe


def _save_entry(dirname, key, compiled, label=""):
    """Serialize + atomically persist one executable; best-effort (a
    full disk or permission error costs the NEXT process a compile,
    never this run)."""
    t0 = time.perf_counter()
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        blob = pickle.dumps(
            {"format": FORMAT_VERSION, "label": label, "payload": payload,
             "in_tree": in_tree, "out_tree": out_tree},
            protocol=pickle.HIGHEST_PROTOCOL)
        os.makedirs(dirname, exist_ok=True)
        from . import io as _io

        _io._atomic_write_bytes(entry_path(dirname, key), blob)
    except Exception as e:
        logger.warning("compile cache save under %s failed (%s: %s); "
                       "continuing uncached", dirname, type(e).__name__, e)
        return False
    _M_SAVE_SECONDS.observe(time.perf_counter() - t0)
    _evict(dirname)
    return True


def _evict(dirname, budget=None):
    """Delete oldest-mtime entries until the dir fits the byte budget
    (``PADDLE_COMPILE_CACHE_MAX_BYTES``; None/0 = unbounded)."""
    budget = max_cache_bytes() if budget is None else budget
    if not budget:
        return 0
    entries = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return 0
    for fn in names:
        if not fn.endswith(ENTRY_SUFFIX):
            continue
        p = os.path.join(dirname, fn)
        try:
            st = os.stat(p)
        except OSError:
            continue
        entries.append((st.st_mtime, st.st_size, p))
    total = sum(sz for _, sz, _ in entries)
    entries.sort()
    evicted = 0
    for _, sz, p in entries:
        if total <= budget:
            break
        try:
            os.remove(p)
        except OSError:
            continue
        total -= sz
        evicted += 1
        _M_EVICTED.inc()
    return evicted


# -- the wrap point -----------------------------------------------------------
def wrap_jit(jfn, key, read_dirs=None, label=""):
    """Give a freshly built ``jax.jit`` callable a disk tier.

    The executor/compiler call this at step-build time (i.e. on an
    in-memory cache MISS). The first real call resolves the executable
    once: try each read dir then the write dir for ``key``; a loadable
    entry skips trace AND compile (disk hit), otherwise the step is
    ``lower().compile()``d live, serialized, and saved (disk miss).
    Subsequent calls go straight to the resolved executable — the same
    object a plain ``jit`` dispatch would use.

    With no cache dir configured (and no ``read_dirs``) or ``key is
    None``, returns ``jfn`` unchanged — the disabled path is
    bit-identical to a build without the cache."""
    write_dir = cache_dir()
    dirs = list(read_dirs or [])
    if write_dir and write_dir not in dirs:
        dirs.append(write_dir)
    if key is None or not dirs:
        return jfn

    resolved = []
    lock = threading.Lock()

    def _resolve(args):
        for d in dirs:
            path = entry_path(d, key)
            if not os.path.exists(path):
                continue
            exe = _load_entry(path)
            if exe is not None:
                _M_DISK_HIT.inc()
                _M_HIT_TIER_DISK.inc()
                return exe
        _M_DISK_MISS.inc()
        _M_MISS_TIER_DISK.inc()
        try:
            compiled = jfn.lower(*args).compile()
        except Exception as e:
            # AOT lowering is the same trace a plain call does, so this
            # is rare (e.g. an executable XLA refuses to serialize);
            # falling back to the undecorated jit keeps the run alive.
            logger.warning("compile cache AOT lower/compile failed "
                           "(%s: %s); running uncached",
                           type(e).__name__, e)
            return jfn
        if write_dir:
            _save_entry(write_dir, key, compiled, label=label)
        return compiled

    def call(*args):
        if not resolved:
            with lock:
                if not resolved:
                    resolved.append(_resolve(args))
        return resolved[0](*args)

    return call


# -- pre-warm (launcher / restart path) ---------------------------------------
def prewarm(dirname=None):
    """Validate + page in every entry under ``dirname`` (default: the
    configured cache dir). Runs in the LAUNCHER before rendezvous
    completes, and in ``restore_on_restart`` — so a reformed gang's
    workers find entries hot in the page cache and corrupt ones already
    quarantined, instead of discovering both inside the downtime
    window. Does NOT load executables onto devices (the launcher must
    not claim the chips). Returns the number of valid entries."""
    dirname = dirname or cache_dir()
    if not dirname or not os.path.isdir(dirname):
        return 0
    ok = 0
    for fn in sorted(os.listdir(dirname)):
        if not fn.endswith(ENTRY_SUFFIX):
            continue
        path = os.path.join(dirname, fn)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            # structural validation only; devices stay untouched
            entry = pickle.loads(blob)  # noqa: sanctioned-cache-read
            if not isinstance(entry, dict) or \
                    entry.get("format") != FORMAT_VERSION or \
                    "payload" not in entry:
                raise ValueError("unrecognized cache entry layout")
        except Exception as e:
            logger.warning("prewarm: quarantining bad cache entry %s "
                           "(%s: %s)", path, type(e).__name__, e)
            _quarantine(path)
            continue
        ok += 1
        _M_PREWARMED.inc()
    return ok


def disk_hit_count():
    """Current value of the disk-hit counter (serving warm-up snapshots
    it around the ladder to report how many compiles a restart skipped)."""
    return _M_DISK_HIT.value
