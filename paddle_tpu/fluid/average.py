"""Host-side weighted running average (reference
``python/paddle/fluid/average.py`` ``WeightedAverage``)."""

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(x):
    return isinstance(x, (int, float, np.ndarray)) or np.isscalar(x)


class WeightedAverage:
    """Accumulate ``value`` with ``weight`` and report the weighted mean.

    Typical use: average per-batch mean losses weighted by batch size
    between ``reset()`` calls (one per epoch).
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError("add(): value must be a number or ndarray")
        if not (np.isscalar(weight) or np.asarray(weight).size == 1):
            raise ValueError("add(): weight must be a number")
        value = np.mean(np.asarray(value, dtype=np.float64))
        weight = float(np.asarray(weight).reshape(()))
        if self.numerator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator == 0.0:
            raise ValueError("eval() before any add() — nothing accumulated")
        return self.numerator / self.denominator
