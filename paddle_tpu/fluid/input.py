"""v1.6 input-layer module (reference ``fluid/input.py``: the new-style
``fluid.input.embedding`` / ``fluid.input.one_hot`` entry points, which
there wrap the v2 ops). The implementations live in ``layers``; this
module keeps the reference's import path working."""

from .layers import embedding, one_hot  # noqa: F401

__all__ = ["one_hot", "embedding"]
