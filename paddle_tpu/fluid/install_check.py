"""Installation self-test (reference ``python/paddle/fluid/install_check.py``
``run_check`` — trains a 2-var linear model single-device and, when more
than one device is visible, again data-parallel)."""

import numpy as np

__all__ = ["run_check"]


def _build_and_train(parallel):
    import jax

    from . import layers, optimizer
    from .compiler import CompiledProgram
    from .executor import Executor
    from .framework import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("install_check_x", [2])
        y = layers.data("install_check_y", [1])
        pred = layers.fc(x, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
        optimizer.SGD(learning_rate=0.01).minimize(loss)

    exe = Executor()
    exe.run(startup)
    ndev = len(jax.devices())
    if parallel:
        prog = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
        batch = 2 * ndev
    else:
        prog = main
        batch = 2
    rng = np.random.RandomState(0)
    feed = {"install_check_x": rng.rand(batch, 2).astype(np.float32),
            "install_check_y": rng.rand(batch, 1).astype(np.float32)}
    (out,) = exe.run(prog, feed=feed, fetch_list=[loss])
    return float(np.asarray(out).reshape(-1)[0])


def run_check():
    """Train one step single-device (and data-parallel when >1 device);
    print diagnostics and raise on failure."""
    import jax

    _build_and_train(parallel=False)
    print("Your paddle_tpu works well on SINGLE device.")
    if len(jax.devices()) > 1:
        _build_and_train(parallel=True)
        print("Your paddle_tpu works well on MULTIPLE devices (%d)."
              % len(jax.devices()))
    print("install_check passed.")
