"""CompiledProgram: attaches a parallel-execution strategy to a Program.

Parity: reference ``python/paddle/fluid/compiler.py:65`` — but where the
reference's ``with_data_parallel`` builds per-device SSA graphs with inserted
NCCL allreduce ops (``multi_devices_graph_pass.cc``), here the SAME lowered
step function is jit-compiled under a ``jax.sharding.Mesh`` with GSPMD
shardings: the batch is sharded over the 'dp' axis, parameters are
replicated, and XLA inserts the gradient all-reduces over ICI automatically.
BuildStrategy/ExecutionStrategy survive as config surface.
"""

import itertools

import numpy as np

from . import compile_cache as _compile_cache
from . import monitor as _monitor
from . import rng as _rng
from .. import jax_compat as _jax_compat
from ..jax_compat import shard_map as _shard_map_compat

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy",
           "UnsupportedStrategyError", "RESERVED_AXES",
           "pipeline_segments"]

_M_RESHARD_REPL = _monitor.counter(
    "state_reshard_replicated_total",
    help="state vars whose shard spec could not be applied on the "
         "current mesh (axis gone or dim not divisible after an "
         "elastic reformation) and fell back to replicated")

_M_PIPE_BUBBLE = _monitor.gauge(
    "pipeline_bubble_fraction",
    help="analytic GPipe bubble fraction (S-1)/(M+S-1) of the most "
         "recently compiled pipeline schedule")

_M_PIPE_MB = _monitor.counter(
    "pipeline_microbatches_total",
    help="microbatches pushed through the pipeline schedule (M per "
         "step, M*k per iters=k window)")

_M_TP_BYTES = _monitor.counter(
    "tp_collective_bytes_total",
    help="analytic bytes moved by the model-axis collectives the "
         "pipeline TP plan inserted (forward psum of each row-parallel "
         "output + backward psum of each column-parallel input, per "
         "microbatch) — an estimate from static shapes, not a NIC "
         "counter")


# Axis names with a fixed role in the 4-axis topology. A user-supplied
# mesh axis may only use one of these names where the strategy actually
# implements that role — otherwise e.g. a "stage" data-parallel axis
# would silently shadow the pipeline schedule's axis.
RESERVED_AXES = frozenset({"host", "stage", "model", "data", "sp"})


class UnsupportedStrategyError(RuntimeError):
    """A CompiledProgram strategy was asked to run in a mode it refuses
    (e.g. ``iters=k`` step batching under ``with_explicit_collectives``).
    Subclasses RuntimeError so pre-existing callers that caught the old
    bare RuntimeError keep working."""


def _validate_mesh_axes(axes, honored, mode, require=()):
    """Reserved-name policy for user-supplied mesh axes: every axis in
    ``RESERVED_AXES`` carries a fixed role, and is only accepted where
    ``mode`` implements that role (``honored``). ``require`` lists axes
    the mode cannot run without."""
    axes = tuple(axes)
    if len(set(axes)) != len(axes):
        raise ValueError("mesh axes %r contain duplicates" % (axes,))
    bad = sorted(a for a in axes if a in RESERVED_AXES and a not in honored)
    if bad:
        raise ValueError(
            "mesh axes %r are reserved names (reserved set: %s) whose "
            "role %s does not implement — it honors %s; rename the axis "
            "or use the strategy that owns it"
            % (bad, sorted(RESERVED_AXES), mode, sorted(honored)))
    missing = [a for a in require if a not in axes]
    if missing:
        raise ValueError(
            "%s requires mesh axes %r; got %r" % (mode, missing, axes))
    return axes


def pipeline_segments(program, block):
    """Split the block's forward ops at the recorded pipeline cuts.

    Returns ``(segments, cut_groups, ad_idx)``: one op-list per stage,
    one tuple of var names per boundary (the activation bundle that
    hops stage r -> r+1 — ``PipelineOptimizer(cut_list=...)`` entries
    that were lists/tuples become multi-var bundles), and the index of
    the ``autodiff`` op (None for a forward-only program). Shared with
    ``tools/stagebalance.py`` so the CLI audits the exact segmentation
    the compiled schedule will run."""
    ops = list(block.ops)
    ad_idx = next((i for i, o in enumerate(ops) if o.type == "autodiff"),
                  None)
    fwd_ops = ops[:ad_idx] if ad_idx is not None else ops
    cut_groups = [tuple(names) for names in
                  getattr(program, "_pipeline_cut_vars", [])]
    producer = {}
    for i, o in enumerate(fwd_ops):
        for nm in o.output_arg_names():
            producer[nm] = i
    segments, start = [], 0
    for grp in cut_groups:
        missing = [n for n in grp if n not in producer]
        if missing:
            raise ValueError(
                "pipeline cut vars %r are not produced by any forward "
                "op" % (missing,))
        end = max(producer[n] for n in grp)
        if end < start:
            raise ValueError(
                "pipeline cut %r is ordered before the previous cut — "
                "cut_list must follow dataflow order" % (grp,))
        segments.append(fwd_ops[start:end + 1])
        start = end + 1
    segments.append(fwd_ops[start:])
    return segments, cut_groups, ad_idx


class _AttrProxy:
    """Present an op with some attrs overridden to a lowering rule —
    the per-shard pipeline TP path patches shape-carrying attrs
    (reshape targets, head counts) without mutating the shared IR."""

    def __init__(self, op, overrides):
        self._op = op
        self._overrides = overrides

    def attr(self, name, default=None):
        if name in self._overrides:
            return self._overrides[name]
        return self._op.attr(name, default)

    def __getattr__(self, name):
        return getattr(self._op, name)


class _ModelAxisPlan:
    """Static Megatron-TP plan for lowering a forward-op sequence on
    per-shard ``model``-axis values inside the (fully manual) pipeline
    shard_map.

    GSPMD does this propagation implicitly from ``ParamAttr(shard=...)``
    layouts; the pipeline schedule runs manual (ppermute over 'stage'
    crashes the partial-auto partitioner on this jaxlib), so the same
    information is derived here ahead of trace: which activation dims
    are sharded over 'model', where the two Megatron region collectives
    go (``copy_to_tp_region`` on each column-parallel input — identity
    forward, psum backward — and ``reduce_from_tp_region`` on each
    row-parallel output), and which shape/head attrs must be divided by
    the axis size for local-shard lowering.

    ``spec``: var name -> sharded dim index (absent = replicated).
    ``copy_in``/``reduce_out``: ids of matmul ops needing a region op.
    ``attr_override``: op id -> {attr: per-shard value}.
    ``psum_bytes``: analytic bytes one microbatch moves through the
    inserted collectives (fwd psums + bwd psums), feeding the
    ``tp_collective_bytes_total`` series.
    """

    _PASSTHROUGH = {"scale", "relu", "gelu", "tanh", "sigmoid", "cast",
                    "dropout", "assign", "square", "sqrt", "exp", "abs",
                    "clip", "leaky_relu"}
    _ELEMENTWISE = {"elementwise_add", "elementwise_sub",
                    "elementwise_mul", "elementwise_div",
                    "elementwise_max", "elementwise_min",
                    "elementwise_pow"}

    def __init__(self, block, fwd_ops, axis, size):
        self.axis = axis
        self.size = int(size)
        self.spec = {}
        self.copy_in = set()
        self.reduce_out = set()
        self.attr_override = {}
        self.psum_bytes = 0
        self._block = block
        for op in fwd_ops:
            self._visit(op)

    # -- helpers -------------------------------------------------------
    def _shape(self, name):
        v = self._block._find_var_recursive(name)
        return tuple(v.shape) if v is not None and v.shape else ()

    def _bytes(self, name):
        shape = self._shape(name)
        if not shape or any(d is None or d < 0 for d in shape):
            return 0
        v = self._block._find_var_recursive(name)
        itemsize = np.dtype(v.dtype).itemsize if v is not None else 4
        return int(np.prod(shape, dtype=np.int64)) * itemsize

    def _param_model_dim(self, name):
        v = self._block._find_var_recursive(name)
        pspec = getattr(v, "shard_spec", None) if v is not None else None
        if not pspec:
            return None
        dims = [d for d, a in enumerate(pspec) if a == self.axis]
        if not dims:
            return None
        if len(dims) > 1:
            raise ValueError(
                "param %r shard spec %r names the %r axis on more than "
                "one dim" % (name, pspec, self.axis))
        return dims[0]

    def _sdim(self, name):
        s = self.spec.get(name)
        if s is None:
            s = self._param_model_dim(name)
            if s is not None:
                self.spec[name] = s
        return s

    def _fail(self, op, why):
        raise ValueError(
            "model-axis propagation cannot lower op %r per-shard: %s. "
            "Either drop the ParamAttr shard spec feeding it or keep "
            "the 'model' axis out of this pipeline mesh." % (op.type, why))

    # -- per-op transfer rules -----------------------------------------
    def _visit(self, op):
        t = op.type
        if t in ("matmul", "mul"):
            return self._visit_matmul(op)
        if t in self._ELEMENTWISE:
            return self._visit_elementwise(op)
        if t in self._PASSTHROUGH:
            s = self._sdim(op.input("X")[0]) if op.input("X") else None
            if s is not None:
                for out in op.output_arg_names():
                    self.spec[out] = s
            return
        if t in ("reshape", "reshape2"):
            return self._visit_reshape(op)
        if t in ("transpose", "transpose2"):
            return self._visit_transpose(op)
        if t == "softmax":
            x = op.input("X")[0]
            s = self._sdim(x)
            if s is not None and s == len(self._shape(x)) - 1:
                self._fail(op, "softmax over the model-sharded dim")
            if s is not None:
                self.spec[op.output("Out")[0]] = s
            return
        if t == "sequence_parallel_attention":
            return self._visit_spa(op)
        if t == "layer_norm":
            if self._sdim(op.input("X")[0]) is not None:
                self._fail(op, "layer_norm over a model-sharded input "
                           "— place it outside the TP block")
            return
        if t == "lookup_table":
            if self._param_model_dim(op.input("W")[0]) is not None:
                self._fail(op, "vocab-parallel embedding is not "
                           "supported on the pipeline model axis (use "
                           "the GSPMD path)")
            return
        if t == "softmax_with_cross_entropy":
            if self._sdim(op.input("Logits")[0]) is not None:
                self._fail(op, "vocab-parallel cross entropy is not "
                           "supported — keep the projection un-sharded")
            return
        # default: refuse if anything sharded flows in; else no-op
        for name in op.input_arg_names():
            if self._sdim(name) is not None:
                self._fail(op, "input %r is sharded over %r and op %r "
                           "has no propagation rule"
                           % (name, self.axis, t))

    def _visit_matmul(self, op):
        xn, yn = op.input("X")[0], op.input("Y")[0]
        xs, ys = self._sdim(xn), self._sdim(yn)
        xr = len(self._shape(xn)) or 2
        yr = len(self._shape(yn)) or 2
        trans_y = bool(op.attr("transpose_Y", False))
        y_contract = yr - 1 if trans_y else yr - 2
        y_out = yr - 2 if trans_y else yr - 1
        out = op.output("Out")[0]
        out_rank = max(xr, yr)
        if xs is None and ys is None:
            return
        # both sharded on the same leading (batch/head) dim: a local
        # batched matmul, no collective (attention scores/context)
        if (xs is not None and ys == xs and xs < xr - 2 and xs < yr - 2):
            self.spec[out] = xs
            return
        if ys == y_out and xs is None and yr == 2:
            # column-parallel weight: activations come in replicated,
            # leave sharded on the output dim; cotangent needs the psum
            self.copy_in.add(id(op))
            self.spec[out] = out_rank - 1
            self.psum_bytes += self._bytes(xn)          # backward psum
            return
        if ys == y_contract and xs == xr - 1 and yr == 2:
            # row-parallel weight: sharded activations contract against
            # the weight's sharded input dim; psum the partial products
            self.reduce_out.add(id(op))
            self.psum_bytes += self._bytes(out)         # forward psum
            return
        if ys is None and xs is not None and xs < xr - 1 and yr == 2:
            self.spec[out] = xs
            return
        self._fail(op, "unsupported matmul sharding X[%s dim %s] @ "
                   "Y[%s dim %s]" % (xn, xs, yn, ys))

    def _visit_elementwise(self, op):
        xn, yn = op.input("X")[0], op.input("Y")[0]
        xs, ys = self._sdim(xn), self._sdim(yn)
        if xs is None and ys is None:
            return
        ax = op.attr("axis", -1)
        if ax not in (None, -1):
            self._fail(op, "sharded elementwise with explicit "
                       "broadcast axis %r" % ax)
        xshape, yshape = self._shape(xn), self._shape(yn)
        rx, ry = len(xshape), len(yshape)
        # trailing-aligned broadcast; out rank = max rank
        big_s, small_s = (xs, ys) if rx >= ry else (ys, xs)
        big_n, small_n = (xn, yn) if rx >= ry else (yn, xn)
        big_shape = xshape if rx >= ry else yshape
        small_shape = yshape if rx >= ry else xshape
        rb, rs = len(big_shape), len(small_shape)
        out = op.output("Out")[0]
        if big_s is not None:
            d_small = big_s - (rb - rs)
            if d_small >= 0:
                if small_s == d_small:
                    pass                        # both sharded, aligned
                elif small_s is None and small_shape[d_small] == 1:
                    pass                        # broadcasts over it
                else:
                    self._fail(op, "operand %r is full-size and "
                               "replicated on %r's sharded dim"
                               % (small_n, big_n))
            elif small_s is not None:
                self._fail(op, "operands sharded on incompatible dims")
            self.spec[out] = big_s
            return
        # only the smaller operand is sharded (a sharded bias onto a
        # replicated activation makes local shapes disagree)
        self._fail(op, "operand %r is sharded but %r is replicated "
                   "full-size" % (small_n, big_n))

    def _visit_reshape(self, op):
        xn = op.input("X")[0]
        s = self._sdim(xn)
        if s is None:
            return
        in_shape = self._shape(xn)
        target = list(op.attr("shape"))
        resolved = [in_shape[i] if d == 0 else d
                    for i, d in enumerate(target)]
        if any(d == -1 for d in resolved):
            numel = int(np.prod(in_shape, dtype=np.int64))
            known = int(np.prod([d for d in resolved if d != -1],
                                dtype=np.int64))
            resolved = [numel // known if d == -1 else d
                        for d in resolved]
        # maximal contiguous groups with equal products map input dims
        # to output dims; the sharded dim must lead its group so the
        # shard stays a contiguous block of the global tensor
        groups, i, j = [], 0, 0
        while i < len(in_shape) and j < len(resolved):
            gi, gj = [i], [j]
            pi, pj = in_shape[i], resolved[j]
            while pi != pj:
                if pi < pj:
                    i += 1
                    gi.append(i)
                    pi *= in_shape[i]
                else:
                    j += 1
                    gj.append(j)
                    pj *= resolved[j]
            groups.append((gi, gj))
            i += 1
            j += 1
        for gi, gj in groups:
            if s not in gi:
                continue
            if s != gi[0] and any(in_shape[d] != 1 for d in gi
                                  if d < s):
                self._fail(op, "reshape merges dims ahead of the "
                           "model-sharded dim")
            lead = gj[0]
            if resolved[lead] % self.size != 0:
                self._fail(op, "reshape target dim %d (size %d) does "
                           "not divide the model axis (%d shards)"
                           % (lead, resolved[lead], self.size))
            override = list(target)
            if override[lead] > 0:
                override[lead] //= self.size
                self.attr_override[id(op)] = {"shape": override}
            self.spec[op.output("Out")[0]] = lead
            return
        self._fail(op, "could not map the sharded dim through reshape")

    def _visit_transpose(self, op):
        xn = op.input("X")[0]
        s = self._sdim(xn)
        if s is None:
            return
        perm = list(op.attr("axis"))
        self.spec[op.output("Out")[0]] = perm.index(s)

    def _visit_spa(self, op):
        specs = {slot: self._sdim(op.input(slot)[0])
                 for slot in ("Q", "K", "V")}
        vals = set(specs.values())
        if vals == {None}:
            return
        last = len(self._shape(op.input("Q")[0])) - 1
        if vals != {last}:
            self._fail(op, "Q/K/V must all be sharded on the packed "
                       "head dim (got %r)" % specs)
        if op.input("Bias") and \
                self._sdim(op.input("Bias")[0]) is not None:
            self._fail(op, "attention bias cannot be model-sharded")
        n_heads = int(op.attr("n_heads"))
        if n_heads % self.size != 0:
            self._fail(op, "n_heads %d not divisible by the model axis "
                       "(%d shards)" % (n_heads, self.size))
        self.attr_override[id(op)] = {"n_heads": n_heads // self.size}
        self.spec[op.output("Out")[0]] = last

    # -- lowering shim -------------------------------------------------
    shape_only = False

    def lower(self, ctx, op):
        """Lower one op on per-shard values, applying this plan's
        region collectives and attr overrides around the registered
        rule. With ``shape_only`` set (the abstract boundary probe,
        which traces OUTSIDE the shard_map so no axis is bound) the
        collectives are skipped — they are shape-identities."""
        from ..parallel import tp as _tp
        from .registry import lower_op

        oid = id(op)
        saved = None
        if oid in self.copy_in and not self.shape_only:
            xn = op.input("X")[0]
            saved = (xn, ctx.env[xn])
            ctx.env[xn] = _tp.copy_to_tp_region(ctx.env[xn], self.axis)
        target = op
        if oid in self.attr_override:
            target = _AttrProxy(op, self.attr_override[oid])
        lower_op(ctx, target)
        if saved is not None:
            ctx.env[saved[0]] = saved[1]
        if oid in self.reduce_out and not self.shape_only:
            on = op.output("Out")[0]
            ctx.env[on] = _tp.reduce_from_tp_region(ctx.env[on],
                                                    self.axis)

    def local_shape(self, name):
        """Per-shard shape of ``name`` (global block shape with the
        sharded dim divided)."""
        shape = list(self._shape(name))
        s = self.spec.get(name)
        if s is not None and 0 <= s < len(shape) and shape[s] > 0:
            shape[s] = shape[s] // self.size
        return tuple(shape)


class BuildStrategy:
    """Reference ``details/build_strategy.h:58``. Knob fates on TPU:

    - ``enable_inplace`` — HONORED: toggles XLA buffer donation of the
      state pytree in every compiled step (off = keep old buffers live).
    - ``sync_batch_norm`` — inherent under GSPMD: a batch sharded over
      'dp' computes batch-norm statistics over the GLOBAL batch (XLA
      reduces across the sharded axis), which is exactly sync-BN; the
      flag is accepted for parity and not consulted.
    - ``fuse_all_reduce_ops`` / ``fuse_elewise_add_act_ops`` /
      ``fuse_all_optimizer_ops`` / ``memory_optimize`` — delegated to
      XLA's fusion/scheduling; accepted, not consulted.
    - ``reduce_strategy``/``gradient_scale_strategy`` — the GSPMD mean
      semantics make per-device grad scaling moot (loss is a global
      mean); accepted, not consulted.
    - ``num_trainers``/``trainer_id`` — multi-process identity comes from
      ``paddle_tpu.distributed`` env bootstrap instead.
    """

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True  # XLA fuses collectives by default
        self.fuse_elewise_add_act_ops = True
        self.fuse_all_optimizer_ops = True
        self.enable_inplace = True  # buffer donation
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """Reference ``details/execution_strategy.h`` — thread counts are
    meaningless under XLA; kept for API parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = True


class CompiledProgram:
    _uid_counter = itertools.count(1)

    def __init__(self, program_or_graph, build_strategy=None):
        self._uid = next(CompiledProgram._uid_counter)
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._places = None
        self._loss_name = None
        self._mesh = None
        self._sharded_feeds = None  # None => shard all feeds on dim 0
        self._seq_feeds = None      # name -> dim sharded over "sp"
        self._seq_fetches = None    # fetch name -> dim pinned to "sp"

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, mesh_axes=("dp",), mesh_shape=None,
                           seq_feeds=None, seq_fetches=None):
        """GSPMD execution. ``mesh_axes``/``mesh_shape`` open the hybrid
        surface: e.g. mesh_axes=("dp","tp"), mesh_shape={"dp":2,"tp":4}
        lays parameters carrying a ``ParamAttr(shard=...)`` spec over the
        'tp' axis (Megatron-style) while the batch shards over 'dp'; XLA
        inserts the TP collectives over ICI.

        ``seq_feeds``: {feed name: dim} — that dim of the feed shards
        over the 'sp' (sequence) axis, composing with the dim-0 'dp'
        batch sharding; long-context programs feed token/cache arrays
        pre-split this way so no single device ever holds the full
        sequence. ``seq_fetches``: {fetch name: dim} — pins those fetch
        outputs to the same 'sp' layout instead of the replicated
        default, so a decode loop can feed a fetched KV cache straight
        back without an all-gather per token."""
        self._is_data_parallel = True
        self._mode = "gspmd"
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        self._mesh_axes = _validate_mesh_axes(
            mesh_axes, honored={"host", "data", "model", "sp"},
            mode="with_data_parallel (GSPMD)")
        self._mesh_shape = dict(mesh_shape) if mesh_shape else None
        self._seq_feeds = dict(seq_feeds) if seq_feeds else None
        self._seq_fetches = dict(seq_fetches) if seq_fetches else None
        return self

    def with_pipeline(self, loss_name=None, places=None, num_microbatches=2,
                      microbatch_vars=None, mesh_axes=("stage",),
                      mesh_shape=None):
        """Pipeline-parallel execution of a Program whose optimizer was
        wrapped in ``PipelineOptimizer`` (cut points recorded on
        ``program._pipeline_cut_vars``).

        TPU-native redesign of the reference's section trainer
        (``PipelineTrainer`` trainer.h:114, scope queues + host threads):
        the forward ops are split into stages at the cut vars; all stages
        execute as ONE SPMD program over the ``stage`` mesh axis — each
        rank selects its stage with ``lax.switch``, activations hop
        rank→rank by ``ppermute``, and the GPipe fill/drain schedule is a
        ``lax.scan`` over ``M + S - 1`` ticks (see
        paddle_tpu/parallel/pipeline.py). The backward schedule falls out
        of differentiating the scan. Contract (GPipe's): the activation
        bundle at every cut shares one pytree of shapes.

        ``mesh_axes`` composes the schedule with the other parallelism
        axes — any of ``("host", "stage", "model", "data")`` with sizes
        in ``mesh_shape``:

        * ``host``/``data`` — hierarchical data parallelism: each
          microbatch's rows shard over these axes (DCN outer, ICI
          inner), grads pmean across them.
        * ``model`` — Megatron tensor parallelism inside every stage:
          params carrying ``ParamAttr(shard=...)`` specs naming 'model'
          are laid out column/row-parallel and the per-shard lowering
          inserts the two region collectives per block.

        Trace/build the model at the PER-SHARD microbatch size b and
        feed the full batch ``[M * data * host * b, ...]``: shape-
        carrying attrs (reshape targets) bake the trace batch, so the
        trace batch must equal what one shard sees per microbatch.
        """
        self._is_data_parallel = True
        self._mode = "pipeline"
        self._loss_name = loss_name
        self._places = places
        axes = _validate_mesh_axes(
            mesh_axes, honored={"host", "stage", "model", "data"},
            mode="with_pipeline", require=("stage",))
        unknown = [a for a in axes if a not in RESERVED_AXES]
        if unknown:
            raise ValueError(
                "with_pipeline mesh axes %r have no role in the "
                "schedule — use only %r" % (
                    unknown, sorted({"host", "stage", "model", "data"})))
        self._mesh_axes = axes
        self._mesh_shape = dict(mesh_shape) if mesh_shape else None
        self._num_microbatches = int(num_microbatches)
        self._microbatch_vars = (set(
            v.name if hasattr(v, "name") else str(v) for v in microbatch_vars)
            if microbatch_vars is not None else None)
        return self

    def with_explicit_collectives(self, loss_name=None, places=None,
                                  mesh_axes=("dp",), mesh_shape=None):
        """SPMD execution via shard_map: every op runs per-shard and the
        program's explicit collective ops (c_allreduce_* etc., inserted by
        the Fleet/collective transpiler) lower to real XLA collectives over
        the named mesh axes. This is the reference's Fleet-collective mode
        (transpiler/collective.py GradAllReduce) on ICI.

        ``mesh_axes``/``mesh_shape`` open the hierarchical surface:
        mesh_axes=("host","device"), mesh_shape={"host":2,"device":4}
        builds the 2-level mesh ``HierarchicalGradAllReduce`` targets —
        ring 0 resolves to 'host' (DCN), ring 1 to 'device' (ICI), and
        feeds/fetch reductions span BOTH axes (the batch shards over all
        8 shards, losses pmean over the full mesh)."""
        self._is_data_parallel = True
        self._mode = "shard_map"
        self._loss_name = loss_name
        self._places = places
        self._mesh_axes = _validate_mesh_axes(
            mesh_axes, honored={"host", "data"},
            mode="with_explicit_collectives (shard_map)")
        self._mesh_shape = dict(mesh_shape) if mesh_shape else None
        return self

    # ------------------------------------------------------------------
    @property
    def mesh(self):
        if not self._is_data_parallel:
            return None
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            devices = self._places if self._places is not None else jax.devices()
            if isinstance(devices, int):
                devices = jax.devices()[:devices]
            axes = getattr(self, "_mesh_axes", ("dp",))
            # single-axis meshes go through the same sizing path so an
            # explicit mesh_shape is honored (and validated), not dropped
            arr = np.array(devices).reshape(
                self._mesh_axis_sizes(len(devices), axes))
            self._mesh = Mesh(arr, axes)
        return self._mesh

    def _mesh_axis_sizes(self, n, axes):
        shape = getattr(self, "_mesh_shape", None)
        if shape:
            missing = [a for a in axes if a not in shape]
            if missing:
                raise ValueError(
                    "mesh_shape %r is missing sizes for mesh axes %r"
                    % (shape, missing))
            sizes = tuple(int(shape[a]) for a in axes)
            if int(np.prod(sizes)) != n:
                raise ValueError(
                    "mesh_shape %r does not multiply to %d devices"
                    % (shape, n))
            return sizes
        # default: first axis takes all devices
        return (n,) + (1,) * (len(axes) - 1)

    def _on_trace_begin(self, ctx):
        if getattr(self, "_mode", "gspmd") == "shard_map":
            mesh = self.mesh
            ctx.shard_axes = list(mesh.axis_names)
            ctx.shard_sizes = dict(mesh.shape)

    def wrap_step(self, step, program, block, feed, fetch_names, state_names,
                  cache_key=None, cache_read_dirs=None):
        # cache_key/cache_read_dirs: the executor's persistent-compile-
        # cache key for this step (fluid/compile_cache.py); each wrapper
        # decorates its inner jit so a restart deserializes instead of
        # recompiling. None => wrap_jit is a no-op passthrough.
        self._cache_key = cache_key
        self._cache_read_dirs = cache_read_dirs
        mode = getattr(self, "_mode", "gspmd")
        if mode == "shard_map":
            return self._wrap_step_shard_map(step, feed, fetch_names,
                                             state_names)
        if mode == "pipeline":
            return self._wrap_step_pipeline(program, block, feed,
                                            fetch_names, state_names)
        return self._wrap_step_gspmd(step, block, feed, fetch_names,
                                     state_names)

    def _cache_wrap(self, jfn, label):
        return _compile_cache.wrap_jit(
            jfn, getattr(self, "_cache_key", None),
            read_dirs=getattr(self, "_cache_read_dirs", None), label=label)

    def _state_pspec(self, block, name):
        """PartitionSpec of a state var on the pipeline mesh — the
        ``shard_spec`` written by ``ParamAttr(shard=...)`` (optimizer
        slots inherit it), replicated otherwise. Strict: a spec naming
        an axis this mesh lacks is a config error at compile time."""
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        var = block._find_var_recursive(name) if block is not None \
            else None
        spec = getattr(var, "shard_spec", None) if var is not None \
            else None
        if spec is None:
            return P()
        missing = [a for a in spec if a is not None
                   and a not in mesh.shape]
        if missing:
            raise ValueError(
                "param %r shard spec %r names mesh axes %r absent from "
                "the mesh %r" % (name, spec, missing, dict(mesh.shape)))
        return P(*spec)

    def _pipeline_mb_names(self, feed):
        """Which feeds are batch-major (sliced into microbatches)?
        Explicit list wins; otherwise infer the batch size as the most
        common leading dim among feeds (a bare divisibility test would
        slice e.g. a (seq, seq) attention mask)."""
        M = self._num_microbatches
        explicit = getattr(self, "_microbatch_vars", None)
        if explicit is not None:
            mb_names = sorted(n for n in feed if n in explicit)
        else:
            from collections import Counter

            lead = Counter(np.shape(feed[n])[0] for n in feed
                           if np.ndim(feed[n]) >= 1)
            batch_dims = [d for d, c in lead.items()
                          if c == max(lead.values())] if lead else []
            if len(batch_dims) != 1:
                raise ValueError(
                    "cannot infer the batch-major feeds (leading dims %r); "
                    "pass microbatch_vars=[...] to with_pipeline" % (lead,))
            bdim = batch_dims[0]
            if bdim % M != 0:
                raise ValueError(
                    "batch dim %d not divisible by num_microbatches %d"
                    % (bdim, M))
            mb_names = sorted(n for n in feed
                              if np.ndim(feed[n]) >= 1
                              and np.shape(feed[n])[0] == bdim)
        full_names = sorted(n for n in feed if n not in mb_names)
        return mb_names, full_names

    def _build_pipeline_kernel(self, program, block, feed, fetch_names,
                               state_names):
        """The per-shard GPipe step body plus its layout metadata —
        shared by the single-step wrapper and the ``iters=k`` window
        wrapper (which scans this kernel).

        The kernel runs fully manual over EVERY mesh axis: 'stage'
        carries the switch/ppermute schedule, 'host'/'data' carry
        hierarchical DP (microbatch rows sharded, grads pmean'd), and
        'model' carries Megatron TP executed per-shard via the
        ``_ModelAxisPlan`` (partial-auto shard_map — GSPMD inside a
        manual region — aborts the SPMD partitioner on this jaxlib as
        soon as a ppermute appears, so nothing here is delegated to
        GSPMD)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from .registry import LowerCtx, lower_op

        mesh = self.mesh
        axis = "stage"
        n_stages = mesh.shape[axis]
        data_axes = tuple(a for a in mesh.axis_names
                          if a in ("host", "data") and mesh.shape[a] > 1)
        dp_total = int(np.prod([mesh.shape[a] for a in data_axes])) \
            if data_axes else 1
        M = self._num_microbatches

        segments, cut_groups, ad_idx = pipeline_segments(program, block)
        if len(cut_groups) != n_stages - 1:
            raise ValueError(
                "PipelineOptimizer recorded %d cut vars but the mesh has "
                "%d stage ranks (need exactly ranks-1 cuts)"
                % (len(cut_groups), n_stages))
        if ad_idx is None:
            raise ValueError(
                "pipeline mode needs a training program (no autodiff op "
                "found — call optimizer.minimize(loss) first)")
        ops = list(block.ops)
        ad_op = ops[ad_idx]
        post_ops = ops[ad_idx + 1:]
        fwd_ops = [o for seg in segments for o in seg]
        wrt = list(ad_op.attr("wrt"))
        grad_names = list(ad_op.attr("grad_names"))
        loss_name = self._loss_name or ad_op.attr("loss")

        plan = None
        if mesh.shape.get("model", 1) > 1:
            plan = _ModelAxisPlan(block, fwd_ops, "model",
                                  mesh.shape["model"])

        def low(ctx, o):
            if plan is not None:
                plan.lower(ctx, o)
            else:
                lower_op(ctx, o)

        mb_names, full_names = self._pipeline_mb_names(feed)
        for n in mb_names:
            b = np.shape(feed[n])[0]
            if b % (M * dp_total) != 0:
                raise ValueError(
                    "batch-major feed %r has %d rows, not divisible by "
                    "num_microbatches (%d) * data-parallel shards (%d); "
                    "feed [M * data * b, ...] rows where b is the "
                    "per-shard microbatch size the model was traced at"
                    % (n, b, M, dp_total))

        state_pspecs = {n: self._state_pspec(block, n)
                        for n in state_names}

        def local_state_shape(name, value):
            shape = list(np.shape(value))
            for d, a in enumerate(state_pspecs[name]):
                if a is not None and d < len(shape):
                    shape[d] //= mesh.shape[a]
            return tuple(shape)

        def _sds(value, shape=None):
            arr_shape = tuple(np.shape(value)) if shape is None else shape
            dtype = np.asarray(value).dtype if not hasattr(value, "dtype") \
                else value.dtype
            return jax.ShapeDtypeStruct(arr_shape, dtype)

        def _probe(env_vals):
            rng = _rng.root_key(0)
            prev, boundaries = None, []
            if plan is not None:
                plan.shape_only = True
            try:
                for r, seg in enumerate(segments):
                    env = dict(env_vals)
                    if r > 0:
                        for nm, v in zip(cut_groups[r - 1], prev):
                            env[nm] = v
                    ctx = LowerCtx(block, env, rng)
                    for o in seg:
                        low(ctx, o)
                    if r < n_stages - 1:
                        prev = tuple(env[nm] for nm in cut_groups[r])
                        boundaries.append(prev)
            finally:
                if plan is not None:
                    plan.shape_only = False
            return boundaries

        return {
            "mesh": mesh, "axis": axis, "n_stages": n_stages,
            "data_axes": data_axes, "dp_total": dp_total, "M": M,
            "segments": segments, "cut_groups": cut_groups,
            "post_ops": post_ops, "wrt": wrt, "grad_names": grad_names,
            "loss_name": loss_name, "plan": plan, "low": low,
            "mb_names": mb_names, "full_names": full_names,
            "state_pspecs": state_pspecs,
            "local_state_shape": local_state_shape,
            "probe": _probe, "sds": _sds,
        }

    def _finish_pipeline_kernel(self, ctxd, block, feed, state,
                                fetch_names, state_names):
        """Bind the boundary templates (needs actual state/feed values
        for local shapes) and return the per-shard kernel."""
        import jax
        import jax.numpy as jnp

        from .registry import LowerCtx, lower_op

        mesh = ctxd["mesh"]
        axis = ctxd["axis"]
        n_stages = ctxd["n_stages"]
        data_axes = ctxd["data_axes"]
        dp_total = ctxd["dp_total"]
        M = ctxd["M"]
        segments = ctxd["segments"]
        cut_groups = ctxd["cut_groups"]
        post_ops = ctxd["post_ops"]
        wrt, grad_names = ctxd["wrt"], ctxd["grad_names"]
        loss_name = ctxd["loss_name"]
        low = ctxd["low"]
        mb_names = ctxd["mb_names"]
        sds = ctxd["sds"]
        local_state_shape = ctxd["local_state_shape"]

        probe_in = {}
        for n in state_names:
            if n not in state:
                continue
            probe_in[n] = sds(state[n], local_state_shape(n, state[n]))
        for n, v in feed.items():
            shape = tuple(np.shape(v))
            if n in mb_names:
                shape = (shape[0] // (M * dp_total),) + shape[1:]
            probe_in[n] = sds(v, shape)
        boundaries = jax.eval_shape(ctxd["probe"], probe_in)
        if boundaries:
            tmpl0 = [(tuple(a.shape), a.dtype) for a in boundaries[0]]
            for r, b in enumerate(boundaries[1:], 1):
                t = [(tuple(a.shape), a.dtype) for a in b]
                if t != tmpl0:
                    raise ValueError(
                        "GPipe uniform-activation contract violated: "
                        "cut %r carries %r but cut %r carries %r — "
                        "every boundary must move one identical pytree "
                        "of activations (pad or re-cut)"
                        % (cut_groups[0], tmpl0, cut_groups[r], t))
            tmpl_sds = tmpl0
        else:
            tmpl_sds = []

        def make_stage(r, seg):
            in_group = cut_groups[r - 1] if r > 0 else None
            out_group = cut_groups[r] if r < n_stages - 1 else None
            is_last = r == n_stages - 1

            def stage(env_base, recv, rng):
                env = dict(env_base)
                if in_group is not None:
                    for nm, val in zip(in_group, recv):
                        env[nm] = val
                ctx = LowerCtx(block, env, rng)
                for o in seg:
                    low(ctx, o)
                zeros = tuple(jnp.zeros(s, d) for s, d in tmpl_sds)
                if is_last:
                    loss = env[loss_name]
                    if loss.ndim > 0:
                        loss = jnp.mean(loss)
                    return zeros, loss
                return (tuple(env[nm] for nm in out_group),
                        jnp.zeros((), "float32"))
            return stage

        stages = [make_stage(r, seg) for r, seg in enumerate(segments)]

        def kernel(params, rest_state, mb_feeds, full_feeds, rng):
            # advance the persistent RNG state every step (dropout masks
            # must differ across steps); stages draw from step_rng
            rng = _rng.wrap_key_data(rng)
            step_rng, next_rng = jax.random.split(rng)
            rng = step_rng
            rank = jax.lax.axis_index(axis)
            perm = [(i, i + 1) for i in range(n_stages - 1)]
            tmpl = tuple(jnp.zeros(s, d) for s, d in tmpl_sds)

            def fwd(ps):
                def tick(carry, t):
                    recv, loss_acc = carry
                    mb = jnp.clip(t - rank, 0, M - 1)
                    env_base = {**rest_state, **ps,
                                **{k: jax.lax.dynamic_index_in_dim(
                                    v, mb, 0, keepdims=False)
                                   for k, v in mb_feeds.items()},
                                **full_feeds}
                    branches = [
                        (lambda eb, xr, rg, _s=s: _s(eb, xr, rg))
                        for s in stages
                    ]
                    y, l = jax.lax.switch(
                        rank, branches, env_base, recv,
                        jax.random.fold_in(rng, t))
                    valid = ((rank == n_stages - 1) & (t - rank >= 0)
                             & (t - rank < M))
                    loss_acc = loss_acc + jnp.where(valid, l, 0.0)
                    recv = tuple(jax.lax.ppermute(leaf, axis, perm)
                                 for leaf in y)
                    return (recv, loss_acc), None

                (_, loss_acc), _ = jax.lax.scan(
                    tick, (tmpl, jnp.zeros((), "float32")),
                    jnp.arange(M + n_stages - 1))
                # return the LOCAL contribution (nonzero on the last rank
                # only): grads flow back across ranks through the ppermute
                # transpose, and one psum below aggregates them — psumming
                # the loss in here too would double-count every cotangent
                return loss_acc / M

            local_loss, grads = jax.value_and_grad(fwd)(params)
            loss = jax.lax.psum(local_loss, axis)
            if data_axes:
                loss = jax.lax.pmean(loss, data_axes)

            def red(g):
                g = jax.lax.psum(g, axis)
                return jax.lax.pmean(g, data_axes) if data_axes else g

            grads = jax.tree_util.tree_map(red, grads)

            # run the post-autodiff ops (optimizer updates etc.) with the
            # pipelined grads bound to the autodiff op's output names;
            # model-sharded params update on their local shards
            env = {**rest_state, **params, **full_feeds,
                   **{k: v[0] for k, v in mb_feeds.items()}}
            env[loss_name] = loss
            for gn, wn in zip(grad_names, wrt):
                env[gn] = grads[wn]
            ctx = LowerCtx(block, env, rng)
            for o in post_ops:
                lower_op(ctx, o)

            new_params = {n: env[n] for n in params}
            new_rest = {n: env[n] for n in rest_state}
            fetches = []
            for fn_ in fetch_names:
                if fn_ == loss_name:
                    fetches.append(loss)
                elif fn_ in env:
                    fetches.append(env[fn_])
                else:
                    raise KeyError(
                        "pipeline mode can fetch the loss or persistable "
                        "vars, not intermediate %r" % fn_)
            return fetches, new_params, new_rest, _rng.key_data(next_rng)

        return kernel

    def _pipeline_specs(self, ctxd, fetch_names, state_names):
        """(in/out PartitionSpecs, fetch specs) for the pipeline
        shard_map: params/state by shard_spec, microbatch rows over the
        data axes, everything else replicated."""
        from jax.sharding import PartitionSpec as P

        data_axes = ctxd["data_axes"]
        state_pspecs = ctxd["state_pspecs"]
        wrt = set(ctxd["wrt"])
        mb_spec = P(None, data_axes) if data_axes else P()
        param_specs = {n: state_pspecs[n] for n in state_names
                       if n in wrt}
        rest_specs = {n: state_pspecs[n] for n in state_names
                      if n not in wrt}
        fetch_specs = [state_pspecs.get(n, P()) for n in fetch_names]
        return mb_spec, param_specs, rest_specs, fetch_specs

    def _wrap_step_pipeline(self, program, block, feed, fetch_names,
                            state_names):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        ctxd = self._build_pipeline_kernel(program, block, feed,
                                           fetch_names, state_names)
        mesh = ctxd["mesh"]
        M, n_stages = ctxd["M"], ctxd["n_stages"]
        dp_total = ctxd["dp_total"]
        mb_names = ctxd["mb_names"]
        plan = ctxd["plan"]
        repl = NamedSharding(mesh, P())
        mb_spec, param_specs, rest_specs, fetch_specs = \
            self._pipeline_specs(ctxd, fetch_names, state_names)
        _M_PIPE_BUBBLE.set((n_stages - 1) / (M + n_stages - 1))
        tp_bytes_per_step = (plan.psum_bytes * M) if plan else 0

        jfn_box = {}

        def fn(state, feed_vals, rng):
            params = {n: state[n] for n in state if n in param_specs}
            rest = {n: state[n] for n in state if n not in param_specs}
            mbf, fullf = {}, {}
            for k, v in feed_vals.items():
                if k in mb_names:
                    arr = jnp.asarray(v)
                    mbf[k] = arr.reshape((M, arr.shape[0] // M)
                                         + arr.shape[1:])
                else:
                    fullf[k] = jnp.asarray(v)
            if "jfn" not in jfn_box:
                kernel = self._finish_pipeline_kernel(
                    ctxd, block, feed_vals, state, fetch_names,
                    state_names)
                # spec dicts keyed by the RUNTIME state split (state may
                # carry vars the trace-time state_names missed)
                jfn_box["p_specs"] = {n: param_specs.get(n, P())
                                      for n in params}
                jfn_box["r_specs"] = {n: rest_specs.get(n, P())
                                      for n in rest}
                smapped = _shard_map_compat(
                    kernel, mesh=mesh,
                    in_specs=(jfn_box["p_specs"], jfn_box["r_specs"],
                              {n: mb_spec for n in mbf},
                              {n: P() for n in fullf}, P()),
                    out_specs=(fetch_specs, jfn_box["p_specs"],
                               jfn_box["r_specs"], P()),
                    check_vma=False)
                donate = ((0, 1) if self._build_strategy.enable_inplace
                          and _jax_compat.SHARD_MAP_DONATION_OK else ())
                jfn_box["jfn"] = self._cache_wrap(
                    jax.jit(smapped, donate_argnums=donate), "pipeline")
            put_state = lambda tree, specs: {
                k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                for k, v in tree.items()}
            fetches, new_params, new_rest, new_rng = jfn_box["jfn"](
                put_state(params, jfn_box["p_specs"]),
                put_state(rest, jfn_box["r_specs"]),
                {k: jax.device_put(v, NamedSharding(mesh, mb_spec))
                 for k, v in mbf.items()},
                {k: jax.device_put(v, repl) for k, v in fullf.items()},
                jax.device_put(rng, repl))
            _M_PIPE_MB.inc(M)
            if tp_bytes_per_step:
                _M_TP_BYTES.inc(tp_bytes_per_step)
            new_state = dict(new_rest)
            new_state.update(new_params)
            return fetches, new_state, new_rng

        return fn

    def _wrap_step_shard_map(self, step, feed, fetch_names, state_names):
        """SPMD per-shard execution; program collectives do the syncing."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        # fetch reductions span the WHOLE mesh: on a hierarchical
        # ("host","device") mesh the loss must average over all H*D
        # shards, not just the first axis
        axis = tuple(mesh.axis_names)
        repl = NamedSharding(mesh, P())

        feed_specs = {n: self.feed_sharding(feed[n]).spec for n in feed}

        def inner(state, feed_vals, rng):
            fetches, new_state, new_rng = step(state, feed_vals, rng)
            # fetches are per-shard; average them for the host (the
            # reference returns the averaged loss across trainers)
            out = []
            for f in fetches:
                if jnp.issubdtype(f.dtype, jnp.floating):
                    out.append(jax.lax.pmean(f, axis))
                else:
                    out.append(jax.lax.pmax(f, axis))
            return out, new_state, new_rng

        smapped = _shard_map_compat(
            inner,
            mesh=mesh,
            in_specs=({n: P() for n in state_names}, feed_specs, P()),
            out_specs=([P() for _ in fetch_names], {n: P() for n in state_names}, P()),
            check_vma=False,
        )
        donate = ((0,) if self._build_strategy.enable_inplace
                  and _jax_compat.SHARD_MAP_DONATION_OK else ())
        jfn = self._cache_wrap(jax.jit(smapped, donate_argnums=donate),
                               "shard_map")
        feed_shardings = {n: NamedSharding(mesh, feed_specs[n]) for n in feed}

        def fn(state, feed_vals, rng):
            state = {k: jax.device_put(v, repl) for k, v in state.items()}
            feed_vals = {k: jax.device_put(v, feed_shardings[k])
                         for k, v in feed_vals.items()}
            rng = jax.device_put(rng, repl)
            return jfn(state, feed_vals, rng)

        return fn

    def feed_sharding(self, value, batch_dim=0, name=None):
        """The ``NamedSharding`` this strategy lays a feed array out
        with — the single source of truth the step wrappers AND the
        ahead-of-time stagers (``fluid.reader.DeviceStager``,
        ``Executor.train_from_dataset``, the ``iters=k`` window
        prefetch) share, so prefetched batches land pre-sharded across
        the mesh instead of funneling through device 0.

        ``batch_dim`` is the axis carrying the batch (1 for an
        ``iters=k`` stacked ``[k, batch, ...]`` feed whose leading axis
        is the iteration index). Returns the batch-sharded layout when
        the strategy shards feeds ('dp' under GSPMD, the first mesh
        axis under shard_map) and the batch dim divides evenly,
        replicated otherwise; ``None`` when the strategy stages feeds
        itself (pipeline mode) or no mesh is attached.

        ``name`` keys the GSPMD ``seq_feeds`` table: a registered feed
        additionally shards that dim over 'sp' (composing with the
        batch-over-'dp' split) when the dim divides the axis size."""
        if not self._is_data_parallel:
            return None
        mode = getattr(self, "_mode", "gspmd")
        if mode == "pipeline":
            return None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        ndim = np.ndim(value)
        seq_feeds = getattr(self, "_seq_feeds", None)
        if (mode == "gspmd" and seq_feeds and name in seq_feeds
                and "sp" in mesh.shape):
            sdim = int(seq_feeds[name])
            if sdim != batch_dim and ndim > sdim and \
                    np.shape(value)[sdim] % mesh.shape["sp"] == 0:
                spec = [None] * ndim
                spec[sdim] = "sp"
                if "dp" in mesh.shape and ndim > batch_dim and \
                        np.shape(value)[batch_dim] % mesh.shape["dp"] == 0:
                    spec[batch_dim] = "dp"
                return NamedSharding(mesh, P(*spec))
        if mode == "shard_map" and len(mesh.axis_names) > 1:
            # hierarchical mesh: the batch shards over EVERY axis (each
            # of the H*D shards is one data-parallel rank); fall back to
            # the leading axis when only its size divides the batch
            axes = tuple(mesh.axis_names)
            total = int(np.prod([mesh.shape[a] for a in axes]))
            if ndim > batch_dim and \
                    np.shape(value)[batch_dim] % total == 0:
                spec = [None] * ndim
                spec[batch_dim] = axes
                return NamedSharding(mesh, P(*spec))
        axis = "dp" if mode == "gspmd" else mesh.axis_names[0]
        if axis in mesh.shape and ndim > batch_dim and \
                np.shape(value)[batch_dim] % mesh.shape[axis] == 0:
            spec = [None] * ndim
            spec[batch_dim] = axis
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    def _state_sharding(self, block, name, mesh, repl, shape=None):
        """Param layout: ``ParamAttr(shard=...)`` specs over the mesh,
        everything else replicated (shared by the single-step and
        step-batched GSPMD wrappers). With ``shape`` given (the restore
        path, where the mesh may have shrunk since the spec was
        written), a spec that no longer fits degrades to replicated —
        counted in ``state_reshard_replicated_total`` — instead of
        raising; compile-time callers pass no shape and keep the strict
        error."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        var = block._find_var_recursive(name) if block is not None \
            else None
        spec = getattr(var, "shard_spec", None) if var is not None \
            else None
        if spec is None:
            return repl
        missing = [a for a in spec if a is not None
                   and a not in mesh.shape]
        if missing:
            if shape is None:
                raise ValueError(
                    "param %r shard spec %r names mesh axes %r absent "
                    "from the mesh %r" % (name, spec, missing,
                                          dict(mesh.shape)))
            _M_RESHARD_REPL.inc()
            import logging

            logging.getLogger(__name__).warning(
                "param %r shard spec %r names mesh axes %r absent from "
                "the current mesh %r; restoring replicated",
                name, spec, missing, dict(mesh.shape))
            return repl
        if shape is not None:
            for d, a in enumerate(spec):
                if a is None:
                    continue
                if d >= len(shape) or shape[d] % mesh.shape[a] != 0:
                    _M_RESHARD_REPL.inc()
                    import logging

                    logging.getLogger(__name__).warning(
                        "param %r shape %r does not divide over mesh "
                        "axis %r (size %d); restoring replicated",
                        name, tuple(shape), a, mesh.shape[a])
                    return repl
        return NamedSharding(mesh, P(*spec))

    def state_sharding(self, block, name, value=None):
        """The ``NamedSharding`` a persistable var takes under this
        strategy — the single source of truth
        ``fluid.io.CheckpointManager.restore`` uses to reshard a
        restored checkpoint onto the CURRENT mesh, which after an
        elastic reformation (``distributed.launch`` shrink-to-
        survivors) may be smaller than the mesh that saved it. With
        ``value`` given, a spec that no longer fits the mesh (axis
        gone, dim not divisible) degrades to replicated instead of
        raising. Returns None when the strategy has no mesh (plain
        program — nothing to reshard onto). Pipeline mode answers too:
        a checkpoint saved 'model'-sharded on a 1x4 GSPMD mesh restores
        onto a 2x2 stage-by-model pipeline mesh through the same
        degradation path (specs whose axes survived reshard, the rest
        replicate and count in ``state_reshard_replicated_total``)."""
        if not self._is_data_parallel:
            return None
        mesh = self.mesh
        if mesh is None:
            return None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        repl = NamedSharding(mesh, P())
        return self._state_sharding(
            block, name, mesh, repl,
            shape=np.shape(value) if value is not None else None)

    def _fetch_sharding(self, name, mesh, repl):
        """Fetch layout: replicated unless registered in ``seq_fetches``
        — those pin the given dim to 'sp' so a decode loop can feed the
        fetched (still-sharded) KV cache straight back without the
        per-token all-gather a replicated fetch would force."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        seq_fetches = getattr(self, "_seq_fetches", None)
        if not seq_fetches or name not in seq_fetches or \
                "sp" not in mesh.shape:
            return repl
        sdim = int(seq_fetches[name])
        spec = [None] * (sdim + 1)
        spec[sdim] = "sp"
        return NamedSharding(mesh, P(*spec))

    def _wrap_step_gspmd(self, step, block, feed, fetch_names, state_names):
        """jit the lowered step under the mesh: batch over 'dp', params
        laid out by their ``shard_spec`` (TP), everything else replicated.
        XLA/GSPMD inserts all collectives (grad allreduce over dp, TP
        gather/reduce-scatter) from these layouts."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        feed_shardings = {n: self.feed_sharding(feed[n], name=n)
                          for n in feed}
        state_shardings = {n: self._state_sharding(block, n, mesh, repl)
                           for n in state_names}
        in_shardings = (
            state_shardings,
            feed_shardings,
            repl,
        )
        # Pin the new-state layouts to the input layouts: a donated state
        # buffer must alias an identically-sharded output, and leaving the
        # state output unconstrained lets XLA pick per-shard layouts that
        # break the aliasing on older jax builds.
        out_shardings = ([self._fetch_sharding(n, mesh, repl)
                          for n in fetch_names], state_shardings, repl)
        donate = (0,) if self._build_strategy.enable_inplace else ()
        jfn = self._cache_wrap(jax.jit(
            step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=donate,
        ), "gspmd")

        def fn(state, feed_vals, rng):
            # Committed single-device arrays (e.g. from the startup program)
            # must be explicitly resharded onto the mesh before the jit call.
            state = {k: jax.device_put(v, state_shardings.get(k, repl))
                     for k, v in state.items()}
            feed_vals = {
                k: jax.device_put(v, feed_shardings[k]) for k, v in feed_vals.items()
            }
            rng = jax.device_put(rng, repl)
            return jfn(state, feed_vals, rng)

        return fn

    def wrap_batched_step(self, batched, block, stacked_feed,
                          invariant_feed, fetch_names, state_names,
                          cache_key=None, cache_read_dirs=None,
                          program=None, iters=None):
        """Step-batched (``iters=k``) execution under this strategy.

        GSPMD: stacked feeds shard their SECOND axis over 'dp' (the
        leading axis is the iteration index the device-side scan
        slices), invariant feeds shard their leading axis like
        single-step feeds, params follow their ``shard_spec``.

        Pipeline: the window scans the GPipe step kernel INSIDE the
        shard_map (``program``/``iters`` required), so k steps of the
        fill/drain schedule run back-to-back on device — results are
        bit-identical to k single ``run()`` calls because the scan body
        IS the single-step kernel.

        shard_map (explicit collectives) schedules its own device-side
        loop and is refused with a typed error."""
        mode = getattr(self, "_mode", "gspmd")
        if mode == "pipeline":
            if program is None:
                raise ValueError(
                    "pipeline iters>1 needs the Program (cut vars live "
                    "on it); callers must pass program=")
            self._cache_key = cache_key
            self._cache_read_dirs = cache_read_dirs
            return self._wrap_batched_pipeline(
                program, block, stacked_feed, invariant_feed,
                fetch_names, state_names, iters)
        if mode != "gspmd":
            raise UnsupportedStrategyError(
                "iters>1 does not support the %r strategy; supported "
                "strategies: 'gspmd' (with_data_parallel) and "
                "'pipeline' (with_pipeline). %r schedules its own "
                "device-side loop — drive steps from the host instead"
                % (mode, mode))
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        state_shardings = {n: self._state_sharding(block, n, mesh, repl)
                           for n in state_names}
        stacked_shardings = {n: self.feed_sharding(stacked_feed[n],
                                                   batch_dim=1)
                             for n in stacked_feed}
        invariant_shardings = {n: self.feed_sharding(invariant_feed[n])
                               for n in invariant_feed}
        self._cache_key = cache_key
        self._cache_read_dirs = cache_read_dirs
        donate = (0,) if self._build_strategy.enable_inplace else ()
        jfn = self._cache_wrap(jax.jit(
            batched,
            in_shardings=(state_shardings, stacked_shardings,
                          invariant_shardings, repl),
            out_shardings=([repl for _ in fetch_names], None, repl),
            donate_argnums=donate,
        ), "gspmd_batched")

        def fn(state, stacked_vals, invariant_vals, rng):
            state = {k: jax.device_put(v, state_shardings.get(k, repl))
                     for k, v in state.items()}
            stacked_vals = {k: jax.device_put(v, stacked_shardings[k])
                            for k, v in stacked_vals.items()}
            invariant_vals = {k: jax.device_put(v, invariant_shardings[k])
                              for k, v in invariant_vals.items()}
            rng = jax.device_put(rng, repl)
            return jfn(state, stacked_vals, invariant_vals, rng)

        return fn

    def _wrap_batched_pipeline(self, program, block, stacked_feed,
                               invariant_feed, fetch_names, state_names,
                               iters):
        """``iters=k`` window over the GPipe kernel: a ``lax.scan`` over
        the k iterations runs INSIDE the shard_map, its body being
        exactly the single-step kernel — so the window's per-step
        results are bit-identical to k single steps (same op order,
        same RNG chain), just without k host round-trips."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        # per-iteration feed template drives the microbatch/full split
        # and the abstract shape probe
        feed_tmpl = {n: v[0] for n, v in stacked_feed.items()}
        feed_tmpl.update(invariant_feed)
        ctxd = self._build_pipeline_kernel(program, block, feed_tmpl,
                                           fetch_names, state_names)
        mesh = ctxd["mesh"]
        M, n_stages = ctxd["M"], ctxd["n_stages"]
        mb_names = ctxd["mb_names"]
        plan = ctxd["plan"]
        data_axes = ctxd["data_axes"]
        if iters is not None:
            k = int(iters)
        elif stacked_feed:
            k = int(np.shape(next(iter(stacked_feed.values())))[0])
        else:
            raise ValueError(
                "pipeline iters>1 with no stacked feeds needs iters=")
        repl = NamedSharding(mesh, P())
        mb_spec, param_specs, rest_specs, fetch_specs = \
            self._pipeline_specs(ctxd, fetch_names, state_names)
        # traj entries carry a leading k axis the per-step spec must skip
        traj_specs = [P(*((None,) + tuple(s))) for s in fetch_specs]
        stk_mb_spec = P(None, None, data_axes) if data_axes else P()
        _M_PIPE_BUBBLE.set((n_stages - 1) / (M + n_stages - 1))
        tp_bytes = (plan.psum_bytes * M * k) if plan else 0
        jfn_box = {}

        def fn(state, stacked_vals, invariant_vals, rng):
            params = {n: state[n] for n in state if n in param_specs}
            rest = {n: state[n] for n in state if n not in param_specs}
            stk_mb, stk_full, inv_mb, inv_full = {}, {}, {}, {}
            for n, v in stacked_vals.items():
                arr = jnp.asarray(v)
                if n in mb_names:
                    stk_mb[n] = arr.reshape(
                        (arr.shape[0], M, arr.shape[1] // M)
                        + arr.shape[2:])
                else:
                    stk_full[n] = arr
            for n, v in invariant_vals.items():
                arr = jnp.asarray(v)
                if n in mb_names:
                    inv_mb[n] = arr.reshape((M, arr.shape[0] // M)
                                            + arr.shape[1:])
                else:
                    inv_full[n] = arr
            if "jfn" not in jfn_box:
                feed0 = {n: v[0] for n, v in stacked_vals.items()}
                feed0.update(invariant_vals)
                kernel = self._finish_pipeline_kernel(
                    ctxd, block, feed0, state, fetch_names, state_names)
                jfn_box["p_specs"] = {n: param_specs.get(n, P())
                                      for n in params}
                jfn_box["r_specs"] = {n: rest_specs.get(n, P())
                                      for n in rest}

                def window(params, rest_state, stk_mb, stk_full,
                           inv_mb, inv_full, rng):
                    def body(carry, xs):
                        p, r, rk = carry
                        mb_i, full_i = xs
                        fetches, p, r, rk = kernel(
                            p, r, {**inv_mb, **mb_i},
                            {**inv_full, **full_i}, rk)
                        return (p, r, rk), fetches

                    (p, r, rk), traj = jax.lax.scan(
                        body, (params, rest_state, rng),
                        (stk_mb, stk_full), length=k)
                    return traj, p, r, rk

                smapped = _shard_map_compat(
                    window, mesh=mesh,
                    in_specs=(jfn_box["p_specs"], jfn_box["r_specs"],
                              {n: stk_mb_spec for n in stk_mb},
                              {n: P() for n in stk_full},
                              {n: mb_spec for n in inv_mb},
                              {n: P() for n in inv_full}, P()),
                    out_specs=(traj_specs, jfn_box["p_specs"],
                               jfn_box["r_specs"], P()),
                    check_vma=False)
                donate = ((0, 1) if self._build_strategy.enable_inplace
                          and _jax_compat.SHARD_MAP_DONATION_OK else ())
                jfn_box["jfn"] = self._cache_wrap(
                    jax.jit(smapped, donate_argnums=donate),
                    "pipeline_batched")
            put = lambda tree, spec_of: {
                kk: jax.device_put(vv, NamedSharding(mesh, spec_of(kk)))
                for kk, vv in tree.items()}
            traj, new_params, new_rest, new_rng = jfn_box["jfn"](
                put(params, jfn_box["p_specs"].__getitem__),
                put(rest, jfn_box["r_specs"].__getitem__),
                put(stk_mb, lambda _n: stk_mb_spec),
                put(stk_full, lambda _n: P()),
                put(inv_mb, lambda _n: mb_spec),
                put(inv_full, lambda _n: P()),
                jax.device_put(rng, repl))
            _M_PIPE_MB.inc(M * k)
            if tp_bytes:
                _M_TP_BYTES.inc(tp_bytes)
            new_state = dict(new_rest)
            new_state.update(new_params)
            return traj, new_state, new_rng

        return fn
