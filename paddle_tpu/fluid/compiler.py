"""CompiledProgram: attaches a parallel-execution strategy to a Program.

Parity: reference ``python/paddle/fluid/compiler.py:65`` — but where the
reference's ``with_data_parallel`` builds per-device SSA graphs with inserted
NCCL allreduce ops (``multi_devices_graph_pass.cc``), here the SAME lowered
step function is jit-compiled under a ``jax.sharding.Mesh`` with GSPMD
shardings: the batch is sharded over the 'dp' axis, parameters are
replicated, and XLA inserts the gradient all-reduces over ICI automatically.
BuildStrategy/ExecutionStrategy survive as config surface.
"""

import itertools

import numpy as np

from . import compile_cache as _compile_cache
from . import monitor as _monitor
from . import rng as _rng
from .. import jax_compat as _jax_compat
from ..jax_compat import shard_map as _shard_map_compat

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]

_M_RESHARD_REPL = _monitor.counter(
    "state_reshard_replicated_total",
    help="state vars whose shard spec could not be applied on the "
         "current mesh (axis gone or dim not divisible after an "
         "elastic reformation) and fell back to replicated")


class BuildStrategy:
    """Reference ``details/build_strategy.h:58``. Knob fates on TPU:

    - ``enable_inplace`` — HONORED: toggles XLA buffer donation of the
      state pytree in every compiled step (off = keep old buffers live).
    - ``sync_batch_norm`` — inherent under GSPMD: a batch sharded over
      'dp' computes batch-norm statistics over the GLOBAL batch (XLA
      reduces across the sharded axis), which is exactly sync-BN; the
      flag is accepted for parity and not consulted.
    - ``fuse_all_reduce_ops`` / ``fuse_elewise_add_act_ops`` /
      ``fuse_all_optimizer_ops`` / ``memory_optimize`` — delegated to
      XLA's fusion/scheduling; accepted, not consulted.
    - ``reduce_strategy``/``gradient_scale_strategy`` — the GSPMD mean
      semantics make per-device grad scaling moot (loss is a global
      mean); accepted, not consulted.
    - ``num_trainers``/``trainer_id`` — multi-process identity comes from
      ``paddle_tpu.distributed`` env bootstrap instead.
    """

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True  # XLA fuses collectives by default
        self.fuse_elewise_add_act_ops = True
        self.fuse_all_optimizer_ops = True
        self.enable_inplace = True  # buffer donation
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """Reference ``details/execution_strategy.h`` — thread counts are
    meaningless under XLA; kept for API parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = True


class CompiledProgram:
    _uid_counter = itertools.count(1)

    def __init__(self, program_or_graph, build_strategy=None):
        self._uid = next(CompiledProgram._uid_counter)
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._places = None
        self._loss_name = None
        self._mesh = None
        self._sharded_feeds = None  # None => shard all feeds on dim 0
        self._seq_feeds = None      # name -> dim sharded over "sp"
        self._seq_fetches = None    # fetch name -> dim pinned to "sp"

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, mesh_axes=("dp",), mesh_shape=None,
                           seq_feeds=None, seq_fetches=None):
        """GSPMD execution. ``mesh_axes``/``mesh_shape`` open the hybrid
        surface: e.g. mesh_axes=("dp","tp"), mesh_shape={"dp":2,"tp":4}
        lays parameters carrying a ``ParamAttr(shard=...)`` spec over the
        'tp' axis (Megatron-style) while the batch shards over 'dp'; XLA
        inserts the TP collectives over ICI.

        ``seq_feeds``: {feed name: dim} — that dim of the feed shards
        over the 'sp' (sequence) axis, composing with the dim-0 'dp'
        batch sharding; long-context programs feed token/cache arrays
        pre-split this way so no single device ever holds the full
        sequence. ``seq_fetches``: {fetch name: dim} — pins those fetch
        outputs to the same 'sp' layout instead of the replicated
        default, so a decode loop can feed a fetched KV cache straight
        back without an all-gather per token."""
        self._is_data_parallel = True
        self._mode = "gspmd"
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        self._mesh_axes = tuple(mesh_axes)
        self._mesh_shape = dict(mesh_shape) if mesh_shape else None
        self._seq_feeds = dict(seq_feeds) if seq_feeds else None
        self._seq_fetches = dict(seq_fetches) if seq_fetches else None
        return self

    def with_pipeline(self, loss_name=None, places=None, num_microbatches=2,
                      microbatch_vars=None):
        """Pipeline-parallel execution of a Program whose optimizer was
        wrapped in ``PipelineOptimizer`` (cut points recorded on
        ``program._pipeline_cut_vars``).

        TPU-native redesign of the reference's section trainer
        (``PipelineTrainer`` trainer.h:114, scope queues + host threads):
        the forward ops are split into stages at the cut vars; all stages
        execute as ONE SPMD program over the ``pp`` mesh axis — each rank
        selects its stage with ``lax.switch``, activations hop rank→rank by
        ``ppermute``, and the GPipe fill/drain schedule is a ``lax.scan``
        over ``M + P - 1`` ticks (see paddle_tpu/parallel/pipeline.py). The
        backward schedule falls out of differentiating the scan. Contract
        (GPipe's): activations at every cut share one shape.
        """
        self._is_data_parallel = True
        self._mode = "pipeline"
        self._loss_name = loss_name
        self._places = places
        self._mesh_axes = ("pp",)
        self._num_microbatches = int(num_microbatches)
        self._microbatch_vars = (set(
            v.name if hasattr(v, "name") else str(v) for v in microbatch_vars)
            if microbatch_vars is not None else None)
        return self

    def with_explicit_collectives(self, loss_name=None, places=None,
                                  mesh_axes=("dp",), mesh_shape=None):
        """SPMD execution via shard_map: every op runs per-shard and the
        program's explicit collective ops (c_allreduce_* etc., inserted by
        the Fleet/collective transpiler) lower to real XLA collectives over
        the named mesh axes. This is the reference's Fleet-collective mode
        (transpiler/collective.py GradAllReduce) on ICI.

        ``mesh_axes``/``mesh_shape`` open the hierarchical surface:
        mesh_axes=("host","device"), mesh_shape={"host":2,"device":4}
        builds the 2-level mesh ``HierarchicalGradAllReduce`` targets —
        ring 0 resolves to 'host' (DCN), ring 1 to 'device' (ICI), and
        feeds/fetch reductions span BOTH axes (the batch shards over all
        8 shards, losses pmean over the full mesh)."""
        self._is_data_parallel = True
        self._mode = "shard_map"
        self._loss_name = loss_name
        self._places = places
        self._mesh_axes = tuple(mesh_axes)
        self._mesh_shape = dict(mesh_shape) if mesh_shape else None
        return self

    # ------------------------------------------------------------------
    @property
    def mesh(self):
        if not self._is_data_parallel:
            return None
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            devices = self._places if self._places is not None else jax.devices()
            if isinstance(devices, int):
                devices = jax.devices()[:devices]
            axes = getattr(self, "_mesh_axes", ("dp",))
            # single-axis meshes go through the same sizing path so an
            # explicit mesh_shape is honored (and validated), not dropped
            arr = np.array(devices).reshape(
                self._mesh_axis_sizes(len(devices), axes))
            self._mesh = Mesh(arr, axes)
        return self._mesh

    def _mesh_axis_sizes(self, n, axes):
        shape = getattr(self, "_mesh_shape", None)
        if shape:
            missing = [a for a in axes if a not in shape]
            if missing:
                raise ValueError(
                    "mesh_shape %r is missing sizes for mesh axes %r"
                    % (shape, missing))
            sizes = tuple(int(shape[a]) for a in axes)
            if int(np.prod(sizes)) != n:
                raise ValueError(
                    "mesh_shape %r does not multiply to %d devices"
                    % (shape, n))
            return sizes
        # default: first axis takes all devices
        return (n,) + (1,) * (len(axes) - 1)

    def _on_trace_begin(self, ctx):
        if getattr(self, "_mode", "gspmd") == "shard_map":
            mesh = self.mesh
            ctx.shard_axes = list(mesh.axis_names)
            ctx.shard_sizes = dict(mesh.shape)

    def wrap_step(self, step, program, block, feed, fetch_names, state_names,
                  cache_key=None, cache_read_dirs=None):
        # cache_key/cache_read_dirs: the executor's persistent-compile-
        # cache key for this step (fluid/compile_cache.py); each wrapper
        # decorates its inner jit so a restart deserializes instead of
        # recompiling. None => wrap_jit is a no-op passthrough.
        self._cache_key = cache_key
        self._cache_read_dirs = cache_read_dirs
        mode = getattr(self, "_mode", "gspmd")
        if mode == "shard_map":
            return self._wrap_step_shard_map(step, feed, fetch_names,
                                             state_names)
        if mode == "pipeline":
            return self._wrap_step_pipeline(program, block, feed,
                                            fetch_names, state_names)
        return self._wrap_step_gspmd(step, block, feed, fetch_names,
                                     state_names)

    def _cache_wrap(self, jfn, label):
        return _compile_cache.wrap_jit(
            jfn, getattr(self, "_cache_key", None),
            read_dirs=getattr(self, "_cache_read_dirs", None), label=label)

    def _wrap_step_pipeline(self, program, block, feed, fetch_names,
                            state_names):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from .registry import LowerCtx, lower_op, registry

        mesh = self.mesh
        axis = mesh.axis_names[0]
        n_stages = mesh.shape[axis]
        M = self._num_microbatches
        cuts = [names[0] for names in
                getattr(program, "_pipeline_cut_vars", [])]
        if len(cuts) != n_stages - 1:
            raise ValueError(
                "PipelineOptimizer recorded %d cut vars but the mesh has %d "
                "pp ranks (need exactly ranks-1 cuts)" % (len(cuts), n_stages))

        ops = block.ops
        ad_idx = next(i for i, o in enumerate(ops) if o.type == "autodiff")
        ad_op = ops[ad_idx]
        fwd_ops, post_ops = ops[:ad_idx], ops[ad_idx + 1:]
        wrt = list(ad_op.attr("wrt"))
        grad_names = list(ad_op.attr("grad_names"))
        loss_name = self._loss_name or ad_op.attr("loss")

        producer = {}
        for i, o in enumerate(fwd_ops):
            for nm in o.output_arg_names():
                producer[nm] = i
        segments, start = [], 0
        for c in cuts:
            segments.append(fwd_ops[start:producer[c] + 1])
            start = producer[c] + 1
        segments.append(fwd_ops[start:])

        def make_stage(seg, out_name, is_last):
            def stage(env_base, x_recv, in_name, rng):
                env = dict(env_base)
                if in_name is not None:
                    env[in_name] = x_recv
                ctx = LowerCtx(block, env, rng)
                for o in seg:
                    lower_op(ctx, o)
                if is_last:
                    loss = env[loss_name]
                    if loss.ndim > 0:
                        loss = jnp.mean(loss)
                    return jnp.zeros_like(x_recv), loss
                return env[out_name], jnp.zeros((), "float32")
            return stage

        stages = []
        for r, seg in enumerate(segments):
            stages.append(make_stage(
                seg, cuts[r] if r < n_stages - 1 else None,
                r == n_stages - 1))
        stage_ins = [None] + cuts  # stage r consumes cuts[r-1]

        # Which feeds are batch-major? Explicit list wins; otherwise infer
        # the batch size as the most common leading dim among feeds (a bare
        # divisibility test would slice e.g. a (seq, seq) attention mask).
        explicit = getattr(self, "_microbatch_vars", None)
        if explicit is not None:
            mb_names = sorted(n for n in feed if n in explicit)
        else:
            from collections import Counter

            lead = Counter(np.shape(feed[n])[0] for n in feed
                           if np.ndim(feed[n]) >= 1)
            batch_dims = [d for d, c in lead.items()
                          if c == max(lead.values())] if lead else []
            if len(batch_dims) != 1:
                raise ValueError(
                    "cannot infer the batch-major feeds (leading dims %r); "
                    "pass microbatch_vars=[...] to with_pipeline" % (lead,))
            bdim = batch_dims[0]
            if bdim % M != 0:
                raise ValueError(
                    "batch dim %d not divisible by num_microbatches %d"
                    % (bdim, M))
            mb_names = sorted(n for n in feed
                              if np.ndim(feed[n]) >= 1
                              and np.shape(feed[n])[0] == bdim)
        full_names = sorted(n for n in feed if n not in mb_names)

        def kernel(params, rest_state, mb_feeds, full_feeds, rng):
            # advance the persistent RNG state every step (dropout masks
            # must differ across steps); stages draw from step_rng
            rng = _rng.wrap_key_data(rng)
            step_rng, next_rng = jax.random.split(rng)
            rng = step_rng
            rank = jax.lax.axis_index(axis)
            perm = [(i, i + 1) for i in range(n_stages - 1)]

            # probe the cut activation shape with microbatch 0 through
            # stage 0 (the GPipe uniform-activation contract); XLA dedups
            # this against the first real tick
            env0 = {**rest_state, **params,
                    **{k: v[0] for k, v in mb_feeds.items()},
                    **full_feeds}
            y0, _ = stages[0](env0, jnp.zeros((), "float32"), None, rng)
            tmpl = jnp.zeros_like(y0)

            def fwd(ps):
                def tick(carry, t):
                    recv, loss_acc = carry
                    mb = jnp.clip(t - rank, 0, M - 1)
                    env_base = {**rest_state, **ps,
                                **{k: jax.lax.dynamic_index_in_dim(
                                    v, mb, 0, keepdims=False)
                                   for k, v in mb_feeds.items()},
                                **full_feeds}
                    branches = [
                        (lambda eb, xr, rg, _s=s, _in=stage_ins[r]:
                         _s(eb, xr, _in, rg))
                        for r, s in enumerate(stages)
                    ]
                    y, l = jax.lax.switch(
                        rank, branches, env_base, recv,
                        jax.random.fold_in(rng, t))
                    valid = ((rank == n_stages - 1) & (t - rank >= 0)
                             & (t - rank < M))
                    loss_acc = loss_acc + jnp.where(valid, l, 0.0)
                    recv = jax.lax.ppermute(y, axis, perm)
                    return (recv, loss_acc), None

                (_, loss_acc), _ = jax.lax.scan(
                    tick, (tmpl, jnp.zeros((), "float32")),
                    jnp.arange(M + n_stages - 1))
                # return the LOCAL contribution (nonzero on the last rank
                # only): grads flow back across ranks through the ppermute
                # transpose, and one psum below aggregates them — psumming
                # the loss in here too would double-count every cotangent
                return loss_acc / M

            local_loss, grads = jax.value_and_grad(fwd)(params)
            loss = jax.lax.psum(local_loss, axis)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, axis), grads)

            # run the post-autodiff ops (optimizer updates etc.) with the
            # pipelined grads bound to the autodiff op's output names
            env = {**rest_state, **params, **full_feeds,
                   **{k: v[0] for k, v in mb_feeds.items()}}
            env[loss_name] = loss
            for gn, wn in zip(grad_names, wrt):
                env[gn] = grads[wn]
            ctx = LowerCtx(block, env, rng)
            for o in post_ops:
                lower_op(ctx, o)

            new_params = {n: env[n] for n in params}
            new_rest = {n: env[n] for n in rest_state}
            fetches = []
            for fn_ in fetch_names:
                if fn_ == loss_name:
                    fetches.append(loss)
                elif fn_ in env:
                    fetches.append(env[fn_])
                else:
                    raise KeyError(
                        "pipeline mode can fetch the loss or persistable "
                        "vars, not intermediate %r" % fn_)
            return fetches, new_params, new_rest, _rng.key_data(next_rng)

        repl = NamedSharding(mesh, P())
        smapped = _shard_map_compat(
            kernel, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P(), P()),
            check_vma=False)
        donate = ((0, 1) if self._build_strategy.enable_inplace
                  and _jax_compat.SHARD_MAP_DONATION_OK else ())
        jfn = self._cache_wrap(jax.jit(smapped, donate_argnums=donate),
                               "pipeline")

        def fn(state, feed_vals, rng):
            params = {n: state[n] for n in state if n in wrt}
            rest = {n: state[n] for n in state if n not in wrt}
            mbf, fullf = {}, {}
            for k, v in feed_vals.items():
                if k in mb_names:
                    arr = jnp.asarray(v)
                    mbf[k] = arr.reshape((M, arr.shape[0] // M)
                                         + arr.shape[1:])
                else:
                    fullf[k] = jnp.asarray(v)
            put = lambda tree: jax.tree_util.tree_map(
                lambda a: jax.device_put(a, repl), tree)
            fetches, new_params, new_rest, new_rng = jfn(
                put(params), put(rest), put(mbf), put(fullf),
                jax.device_put(rng, repl))
            new_state = dict(new_rest)
            new_state.update(new_params)
            return fetches, new_state, new_rng

        return fn

    def _wrap_step_shard_map(self, step, feed, fetch_names, state_names):
        """SPMD per-shard execution; program collectives do the syncing."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        # fetch reductions span the WHOLE mesh: on a hierarchical
        # ("host","device") mesh the loss must average over all H*D
        # shards, not just the first axis
        axis = tuple(mesh.axis_names)
        repl = NamedSharding(mesh, P())

        feed_specs = {n: self.feed_sharding(feed[n]).spec for n in feed}

        def inner(state, feed_vals, rng):
            fetches, new_state, new_rng = step(state, feed_vals, rng)
            # fetches are per-shard; average them for the host (the
            # reference returns the averaged loss across trainers)
            out = []
            for f in fetches:
                if jnp.issubdtype(f.dtype, jnp.floating):
                    out.append(jax.lax.pmean(f, axis))
                else:
                    out.append(jax.lax.pmax(f, axis))
            return out, new_state, new_rng

        smapped = _shard_map_compat(
            inner,
            mesh=mesh,
            in_specs=({n: P() for n in state_names}, feed_specs, P()),
            out_specs=([P() for _ in fetch_names], {n: P() for n in state_names}, P()),
            check_vma=False,
        )
        donate = ((0,) if self._build_strategy.enable_inplace
                  and _jax_compat.SHARD_MAP_DONATION_OK else ())
        jfn = self._cache_wrap(jax.jit(smapped, donate_argnums=donate),
                               "shard_map")
        feed_shardings = {n: NamedSharding(mesh, feed_specs[n]) for n in feed}

        def fn(state, feed_vals, rng):
            state = {k: jax.device_put(v, repl) for k, v in state.items()}
            feed_vals = {k: jax.device_put(v, feed_shardings[k])
                         for k, v in feed_vals.items()}
            rng = jax.device_put(rng, repl)
            return jfn(state, feed_vals, rng)

        return fn

    def feed_sharding(self, value, batch_dim=0, name=None):
        """The ``NamedSharding`` this strategy lays a feed array out
        with — the single source of truth the step wrappers AND the
        ahead-of-time stagers (``fluid.reader.DeviceStager``,
        ``Executor.train_from_dataset``, the ``iters=k`` window
        prefetch) share, so prefetched batches land pre-sharded across
        the mesh instead of funneling through device 0.

        ``batch_dim`` is the axis carrying the batch (1 for an
        ``iters=k`` stacked ``[k, batch, ...]`` feed whose leading axis
        is the iteration index). Returns the batch-sharded layout when
        the strategy shards feeds ('dp' under GSPMD, the first mesh
        axis under shard_map) and the batch dim divides evenly,
        replicated otherwise; ``None`` when the strategy stages feeds
        itself (pipeline mode) or no mesh is attached.

        ``name`` keys the GSPMD ``seq_feeds`` table: a registered feed
        additionally shards that dim over 'sp' (composing with the
        batch-over-'dp' split) when the dim divides the axis size."""
        if not self._is_data_parallel:
            return None
        mode = getattr(self, "_mode", "gspmd")
        if mode == "pipeline":
            return None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        ndim = np.ndim(value)
        seq_feeds = getattr(self, "_seq_feeds", None)
        if (mode == "gspmd" and seq_feeds and name in seq_feeds
                and "sp" in mesh.shape):
            sdim = int(seq_feeds[name])
            if sdim != batch_dim and ndim > sdim and \
                    np.shape(value)[sdim] % mesh.shape["sp"] == 0:
                spec = [None] * ndim
                spec[sdim] = "sp"
                if "dp" in mesh.shape and ndim > batch_dim and \
                        np.shape(value)[batch_dim] % mesh.shape["dp"] == 0:
                    spec[batch_dim] = "dp"
                return NamedSharding(mesh, P(*spec))
        if mode == "shard_map" and len(mesh.axis_names) > 1:
            # hierarchical mesh: the batch shards over EVERY axis (each
            # of the H*D shards is one data-parallel rank); fall back to
            # the leading axis when only its size divides the batch
            axes = tuple(mesh.axis_names)
            total = int(np.prod([mesh.shape[a] for a in axes]))
            if ndim > batch_dim and \
                    np.shape(value)[batch_dim] % total == 0:
                spec = [None] * ndim
                spec[batch_dim] = axes
                return NamedSharding(mesh, P(*spec))
        axis = "dp" if mode == "gspmd" else mesh.axis_names[0]
        if axis in mesh.shape and ndim > batch_dim and \
                np.shape(value)[batch_dim] % mesh.shape[axis] == 0:
            spec = [None] * ndim
            spec[batch_dim] = axis
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    def _state_sharding(self, block, name, mesh, repl, shape=None):
        """Param layout: ``ParamAttr(shard=...)`` specs over the mesh,
        everything else replicated (shared by the single-step and
        step-batched GSPMD wrappers). With ``shape`` given (the restore
        path, where the mesh may have shrunk since the spec was
        written), a spec that no longer fits degrades to replicated —
        counted in ``state_reshard_replicated_total`` — instead of
        raising; compile-time callers pass no shape and keep the strict
        error."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        var = block._find_var_recursive(name) if block is not None \
            else None
        spec = getattr(var, "shard_spec", None) if var is not None \
            else None
        if spec is None:
            return repl
        missing = [a for a in spec if a is not None
                   and a not in mesh.shape]
        if missing:
            if shape is None:
                raise ValueError(
                    "param %r shard spec %r names mesh axes %r absent "
                    "from the mesh %r" % (name, spec, missing,
                                          dict(mesh.shape)))
            _M_RESHARD_REPL.inc()
            import logging

            logging.getLogger(__name__).warning(
                "param %r shard spec %r names mesh axes %r absent from "
                "the current mesh %r; restoring replicated",
                name, spec, missing, dict(mesh.shape))
            return repl
        if shape is not None:
            for d, a in enumerate(spec):
                if a is None:
                    continue
                if d >= len(shape) or shape[d] % mesh.shape[a] != 0:
                    _M_RESHARD_REPL.inc()
                    import logging

                    logging.getLogger(__name__).warning(
                        "param %r shape %r does not divide over mesh "
                        "axis %r (size %d); restoring replicated",
                        name, tuple(shape), a, mesh.shape[a])
                    return repl
        return NamedSharding(mesh, P(*spec))

    def state_sharding(self, block, name, value=None):
        """The ``NamedSharding`` a persistable var takes under this
        strategy — the single source of truth
        ``fluid.io.CheckpointManager.restore`` uses to reshard a
        restored checkpoint onto the CURRENT mesh, which after an
        elastic reformation (``distributed.launch`` shrink-to-
        survivors) may be smaller than the mesh that saved it. With
        ``value`` given, a spec that no longer fits the mesh (axis
        gone, dim not divisible) degrades to replicated instead of
        raising. Returns None when the strategy has no mesh (plain
        program / pipeline mode — nothing to reshard onto)."""
        if not self._is_data_parallel or \
                getattr(self, "_mode", "gspmd") == "pipeline":
            return None
        mesh = self.mesh
        if mesh is None:
            return None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        repl = NamedSharding(mesh, P())
        return self._state_sharding(
            block, name, mesh, repl,
            shape=np.shape(value) if value is not None else None)

    def _fetch_sharding(self, name, mesh, repl):
        """Fetch layout: replicated unless registered in ``seq_fetches``
        — those pin the given dim to 'sp' so a decode loop can feed the
        fetched (still-sharded) KV cache straight back without the
        per-token all-gather a replicated fetch would force."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        seq_fetches = getattr(self, "_seq_fetches", None)
        if not seq_fetches or name not in seq_fetches or \
                "sp" not in mesh.shape:
            return repl
        sdim = int(seq_fetches[name])
        spec = [None] * (sdim + 1)
        spec[sdim] = "sp"
        return NamedSharding(mesh, P(*spec))

    def _wrap_step_gspmd(self, step, block, feed, fetch_names, state_names):
        """jit the lowered step under the mesh: batch over 'dp', params
        laid out by their ``shard_spec`` (TP), everything else replicated.
        XLA/GSPMD inserts all collectives (grad allreduce over dp, TP
        gather/reduce-scatter) from these layouts."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        feed_shardings = {n: self.feed_sharding(feed[n], name=n)
                          for n in feed}
        state_shardings = {n: self._state_sharding(block, n, mesh, repl)
                           for n in state_names}
        in_shardings = (
            state_shardings,
            feed_shardings,
            repl,
        )
        # Pin the new-state layouts to the input layouts: a donated state
        # buffer must alias an identically-sharded output, and leaving the
        # state output unconstrained lets XLA pick per-shard layouts that
        # break the aliasing on older jax builds.
        out_shardings = ([self._fetch_sharding(n, mesh, repl)
                          for n in fetch_names], state_shardings, repl)
        donate = (0,) if self._build_strategy.enable_inplace else ()
        jfn = self._cache_wrap(jax.jit(
            step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=donate,
        ), "gspmd")

        def fn(state, feed_vals, rng):
            # Committed single-device arrays (e.g. from the startup program)
            # must be explicitly resharded onto the mesh before the jit call.
            state = {k: jax.device_put(v, state_shardings.get(k, repl))
                     for k, v in state.items()}
            feed_vals = {
                k: jax.device_put(v, feed_shardings[k]) for k, v in feed_vals.items()
            }
            rng = jax.device_put(rng, repl)
            return jfn(state, feed_vals, rng)

        return fn

    def wrap_batched_step(self, batched, block, stacked_feed,
                          invariant_feed, fetch_names, state_names,
                          cache_key=None, cache_read_dirs=None):
        """Step-batched (``iters=k``) execution under this strategy.
        GSPMD only: stacked feeds shard their SECOND axis over 'dp' (the
        leading axis is the iteration index the device-side scan slices),
        invariant feeds shard their leading axis like single-step feeds,
        params follow their ``shard_spec``. shard_map and pipeline modes
        already schedule their own device-side loops, so a scan around
        them is refused rather than half-supported."""
        mode = getattr(self, "_mode", "gspmd")
        if mode != "gspmd":
            raise RuntimeError(
                "iters>1 supports plain programs and GSPMD data/hybrid "
                "parallelism (with_data_parallel); %r mode schedules its "
                "own device-side loop — drive steps from the host "
                "instead" % mode)
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        state_shardings = {n: self._state_sharding(block, n, mesh, repl)
                           for n in state_names}
        stacked_shardings = {n: self.feed_sharding(stacked_feed[n],
                                                   batch_dim=1)
                             for n in stacked_feed}
        invariant_shardings = {n: self.feed_sharding(invariant_feed[n])
                               for n in invariant_feed}
        self._cache_key = cache_key
        self._cache_read_dirs = cache_read_dirs
        donate = (0,) if self._build_strategy.enable_inplace else ()
        jfn = self._cache_wrap(jax.jit(
            batched,
            in_shardings=(state_shardings, stacked_shardings,
                          invariant_shardings, repl),
            out_shardings=([repl for _ in fetch_names], None, repl),
            donate_argnums=donate,
        ), "gspmd_batched")

        def fn(state, stacked_vals, invariant_vals, rng):
            state = {k: jax.device_put(v, state_shardings.get(k, repl))
                     for k, v in state.items()}
            stacked_vals = {k: jax.device_put(v, stacked_shardings[k])
                            for k, v in stacked_vals.items()}
            invariant_vals = {k: jax.device_put(v, invariant_shardings[k])
                              for k, v in invariant_vals.items()}
            rng = jax.device_put(rng, repl)
            return jfn(state, stacked_vals, invariant_vals, rng)

        return fn
