"""CompiledProgram: attaches a parallel-execution strategy to a Program.

Parity: reference ``python/paddle/fluid/compiler.py:65`` — but where the
reference's ``with_data_parallel`` builds per-device SSA graphs with inserted
NCCL allreduce ops (``multi_devices_graph_pass.cc``), here the SAME lowered
step function is jit-compiled under a ``jax.sharding.Mesh`` with GSPMD
shardings: the batch is sharded over the 'dp' axis, parameters are
replicated, and XLA inserts the gradient all-reduces over ICI automatically.
BuildStrategy/ExecutionStrategy survive as config surface.
"""

import numpy as np

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """Reference ``details/build_strategy.h:58``. Most knobs are XLA's job
    now; kept ones change sharding/fusion behavior."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True  # XLA fuses collectives by default
        self.fuse_elewise_add_act_ops = True
        self.fuse_all_optimizer_ops = True
        self.enable_inplace = True  # buffer donation
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """Reference ``details/execution_strategy.h`` — thread counts are
    meaningless under XLA; kept for API parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = True


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._places = None
        self._loss_name = None
        self._mesh = None
        self._sharded_feeds = None  # None => shard all feeds on dim 0

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._mode = "gspmd"
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        return self

    def with_explicit_collectives(self, loss_name=None, places=None,
                                  mesh_axes=("dp",)):
        """SPMD execution via shard_map: every op runs per-shard and the
        program's explicit collective ops (c_allreduce_* etc., inserted by
        the Fleet/collective transpiler) lower to real XLA collectives over
        the named mesh axes. This is the reference's Fleet-collective mode
        (transpiler/collective.py GradAllReduce) on ICI."""
        self._is_data_parallel = True
        self._mode = "shard_map"
        self._loss_name = loss_name
        self._places = places
        self._mesh_axes = tuple(mesh_axes)
        return self

    # ------------------------------------------------------------------
    @property
    def mesh(self):
        if not self._is_data_parallel:
            return None
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            devices = self._places if self._places is not None else jax.devices()
            if isinstance(devices, int):
                devices = jax.devices()[:devices]
            axes = getattr(self, "_mesh_axes", ("dp",))
            if len(axes) == 1:
                self._mesh = Mesh(np.array(devices), axes)
            else:
                arr = np.array(devices).reshape(
                    self._mesh_axis_sizes(len(devices), axes))
                self._mesh = Mesh(arr, axes)
        return self._mesh

    @staticmethod
    def _mesh_axis_sizes(n, axes):
        # default: first axis takes all devices unless sizes were provided
        return (n,) + (1,) * (len(axes) - 1)

    def _on_trace_begin(self, ctx):
        if getattr(self, "_mode", "gspmd") == "shard_map":
            mesh = self.mesh
            ctx.shard_axes = list(mesh.axis_names)
            ctx.shard_sizes = dict(mesh.shape)

    def wrap_step(self, step, program, block, feed, fetch_names, state_names):
        if getattr(self, "_mode", "gspmd") == "shard_map":
            return self._wrap_step_shard_map(step, feed, fetch_names,
                                             state_names)
        return self._wrap_step_gspmd(step, feed, fetch_names, state_names)

    def _wrap_step_shard_map(self, step, feed, fetch_names, state_names):
        """SPMD per-shard execution; program collectives do the syncing."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        axis = mesh.axis_names[0]
        repl = NamedSharding(mesh, P())

        def feed_spec(name):
            arr = feed[name]
            ndim = np.ndim(arr)
            if ndim >= 1 and np.shape(arr)[0] % mesh.shape[axis] == 0:
                return P(axis, *([None] * (ndim - 1)))
            return P()

        feed_specs = {n: feed_spec(n) for n in feed}

        def inner(state, feed_vals, rng):
            fetches, new_state, new_rng = step(state, feed_vals, rng)
            # fetches are per-shard; average them for the host (the
            # reference returns the averaged loss across trainers)
            out = []
            for f in fetches:
                if jnp.issubdtype(f.dtype, jnp.floating):
                    out.append(jax.lax.pmean(f, axis))
                else:
                    out.append(jax.lax.pmax(f, axis))
            return out, new_state, new_rng

        smapped = jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=({n: P() for n in state_names}, feed_specs, P()),
            out_specs=([P() for _ in fetch_names], {n: P() for n in state_names}, P()),
            check_vma=False,
        )
        jfn = jax.jit(smapped, donate_argnums=(0,))
        feed_shardings = {n: NamedSharding(mesh, feed_specs[n]) for n in feed}

        def fn(state, feed_vals, rng):
            state = {k: jax.device_put(v, repl) for k, v in state.items()}
            feed_vals = {k: jax.device_put(v, feed_shardings[k])
                         for k, v in feed_vals.items()}
            rng = jax.device_put(rng, repl)
            return jfn(state, feed_vals, rng)

        return fn

    def _wrap_step_gspmd(self, step, feed, fetch_names, state_names):
        """jit the lowered step under the mesh with DP shardings."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        def feed_sharding(name):
            arr = feed[name]
            ndim = np.ndim(arr)
            if ndim >= 1 and np.shape(arr)[0] % mesh.shape["dp"] == 0:
                return NamedSharding(mesh, P("dp", *([None] * (ndim - 1))))
            return repl

        feed_shardings = {n: feed_sharding(n) for n in feed}
        in_shardings = (
            {n: repl for n in state_names},
            feed_shardings,
            repl,
        )
        out_shardings = ([repl for _ in fetch_names], None, repl)
        jfn = jax.jit(
            step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0,),
        )

        def fn(state, feed_vals, rng):
            # Committed single-device arrays (e.g. from the startup program)
            # must be explicitly resharded onto the mesh before the jit call.
            state = {k: jax.device_put(v, repl) for k, v in state.items()}
            feed_vals = {
                k: jax.device_put(v, feed_shardings[k]) for k, v in feed_vals.items()
            }
            rng = jax.device_put(rng, repl)
            return jfn(state, feed_vals, rng)

        return fn
