"""DataLoader / PyReader: the host->device input pipeline.

Parity: reference ``python/paddle/fluid/reader.py`` (``DataLoader:73``
``from_generator``, ``GeneratorLoader:298``, ``PyReader:583``) backed by
C++ ``LoDTensorBlockingQueue`` + ``buffered_reader`` (pre-H2D transfer on a
CUDA stream). TPU-native: a background ``DeviceStager`` thread assembles
numpy batches and stages them on device with ``jax.device_put`` ahead of
consumption — the double-buffer H2D overlap matters even more here because
the chip can sit behind a high-latency host link (see bench.py); the
executor accepts the staged ``jax.Array`` feeds untouched.

Staging is SHARDING-AWARE: pass ``sharding=`` (a ``CompiledProgram``, a
{name: Sharding} dict, or a ``fn(name, value) -> Sharding|None``) and each
feed lands pre-laid-out with the program's GSPMD feed ``NamedSharding``
(``CompiledProgram.feed_sharding``) instead of funneling through device 0 —
a data-parallel program then consumes the prefetched batch with zero
resharding copies.
"""

import os as _os
import queue as _queue
import threading
import time as _time

import numpy as np

from . import monitor as _monitor
from . import resilience as _resilience
from .framework import Variable

__all__ = ["DataLoader", "PyReader", "GeneratorLoader", "DeviceStager",
           "stage_feed", "WorkerInfo", "get_worker_info"]

# -- monitor series (process-wide; see fluid/monitor.py) ----------------------
_M_BATCHES = _monitor.counter(
    "reader_batches_total",
    help="batches produced by DataLoader/GeneratorLoader")
_M_STALLS = _monitor.counter(
    "reader_queue_full_total",
    help="producer stalls: the prefetch queue was full when a batch "
         "was ready (consumer is the bottleneck)")
_M_FEED_SECONDS = _monitor.histogram(
    "reader_feed_seconds",
    help="batch assembly + device staging time (_to_feed)")
_M_PREFETCH_DEPTH = _monitor.gauge(
    "reader_prefetch_depth",
    help="staged batches queued ahead of the consumer (DeviceStager "
         "queue occupancy; capacity-bounded)")
_M_PREFETCH_STALL = _monitor.histogram(
    "reader_prefetch_stall_seconds",
    help="consumer wait on the DeviceStager queue (0 when the next "
         "staged batch was already waiting — the prefetch kept up)")

# transient staging failures (a device_put hiccup on a flaky host link,
# an injected reader.stage fault) are retried with backoff inside the
# producer thread instead of killing the whole input pipeline; attempts
# are tunable via PADDLE_STAGE_RETRIES (>=1), and every retry/exhaustion
# is counted under site="reader.stage" in monitor
_STAGE_RETRY = _resilience.Retry(
    max_attempts=max(1, int(_os.environ.get("PADDLE_STAGE_RETRIES", "3"))),
    base_delay=0.05, max_delay=1.0,
    retryable=_resilience.TransientError, name="reader.stage")


def _as_sharding_fn(sharding):
    """Normalize the ``sharding=`` surface to ``fn(name, value) ->
    Sharding|None``: None passes through, a ``CompiledProgram`` resolves
    via its ``feed_sharding``, a dict looks names up, a callable is used
    as-is."""
    if sharding is None:
        return None
    if hasattr(sharding, "feed_sharding"):  # CompiledProgram strategy
        return lambda name, value: sharding.feed_sharding(value, name=name)
    if isinstance(sharding, dict):
        return lambda name, value: sharding.get(name)
    if callable(sharding):
        return sharding
    raise TypeError(
        "sharding must be None, a CompiledProgram, a {name: Sharding} "
        "dict, or fn(name, value) -> Sharding; got %r" % (sharding,))


def stage_feed(feed, sharding_fn=None):
    """Sharding-aware H2D staging of one feed dict: every ndarray /
    jax.Array value is ``jax.device_put`` with the sharding
    ``sharding_fn(name, value)`` resolves (plain single-device put when
    the fn is absent or returns None); non-array values (LoDTensor etc.)
    pass through raw — the executor decomposes those itself."""
    import jax

    from . import faults as _faults

    _faults.check("reader.stage")
    out = {}
    for name, value in feed.items():
        if isinstance(value, (np.ndarray, jax.Array)):
            s = sharding_fn(name, value) if sharding_fn is not None else None
            value = jax.device_put(value, s) if s is not None \
                else jax.device_put(value)
        out[name] = value
    return out


class DeviceStager:
    """Bounded ahead-of-time staging pipeline: a producer thread pulls
    items from ``source``, runs ``transform`` (batch assembly and/or the
    sharding-aware ``jax.device_put``), and hands results over a bounded
    queue — H2D transfer for batch i+1 overlaps the device's step i, and
    ``reader_prefetch_depth`` reports how far ahead it is running.

    The thread is deliberately NON-daemon: a stager that outlives its
    pipeline is a bug (tests/conftest.py fails any test that leaks one).
    Iterate to exhaustion or call ``close()`` — close() is idempotent,
    unblocks a producer stalled on a full queue, and joins the thread.
    Producer exceptions re-raise in the consumer."""

    _END = object()

    def __init__(self, source, transform=None, capacity=2, name="stager"):
        self._q = _queue.Queue(maxsize=max(1, int(capacity)))
        self._stop = threading.Event()
        self._done = False
        self._transform = transform
        self._source = iter(source)
        self._thread = threading.Thread(
            target=self._produce, name="paddle-device-stager[%s]" % name,
            daemon=False)
        self._thread.start()

    # -- producer side --------------------------------------------------
    def _put(self, item):
        # consumer-bound: count the stall once per batch — checked up
        # front because the blocking put below can absorb a short stall
        # inside its timeout without ever raising Full
        if self._q.full():
            _M_STALLS.inc()
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                _M_PREFETCH_DEPTH.set(self._q.qsize())
                return True
            except _queue.Full:
                pass
        return False

    def _produce(self):
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                if self._transform is not None:
                    # transient staging failures retry with backoff here,
                    # on the producer thread, so a device_put hiccup
                    # doesn't tear down the whole input pipeline
                    item = _STAGE_RETRY.call(self._transform, item)
                if not self._put(item):
                    return
        except BaseException as e:  # background thread: stored and re-raised on the consumer side
            self._put(("__stager_error__", e))
        finally:
            self._put(self._END)

    # -- consumer side --------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = _time.perf_counter()
        item = self._q.get()
        _M_PREFETCH_STALL.observe(_time.perf_counter() - t0)
        _M_PREFETCH_DEPTH.set(self._q.qsize())
        if item is self._END:
            self.close()
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "__stager_error__":
            self.close()
            raise item[1]
        return item

    def close(self):
        """Stop the producer and join its thread. Items still queued are
        dropped (an abandoned prefetch is by definition ahead of what
        the consumer wanted)."""
        if self._done and not self._thread.is_alive():
            return
        self._done = True
        self._stop.set()
        # drain so a producer blocked on a full queue can observe _stop
        while True:
            try:
                self._q.get_nowait()
            except _queue.Empty:
                break
        self._thread.join()
        _M_PREFETCH_DEPTH.set(0)


class WorkerInfo:
    """Identity of the current DataLoader worker process. A generator
    that wants to avoid duplicate parsing shards its own input by
    ``get_worker_info()`` and then calls ``mark_sharded()`` so the loader
    keeps every batch it yields instead of round-robin filtering."""

    def __init__(self, rank, num_workers):
        self.id = rank
        self.num_workers = num_workers
        self.consumed_shard = False

    def mark_sharded(self):
        self.consumed_shard = True


_worker_info = None


def get_worker_info():
    """None in the main process; a WorkerInfo inside an mp worker."""
    return _worker_info


class GeneratorLoader:
    """Iterable loader: wraps a sample/batch generator into prefetched,
    device-staged feed dicts. ``use_double_buffer=False`` turns BOTH the
    prefetch thread and the ahead-of-time device staging off — every
    batch assembles synchronously in the consumer and reaches the
    executor as host arrays (staged at dispatch)."""

    def __init__(self, feed_list, capacity=4, stage_on_device=True,
                 use_multiprocess=False, num_workers=2,
                 use_double_buffer=True, sharding=None):
        self._feed_names = [v.name if isinstance(v, Variable) else str(v)
                            for v in feed_list]
        self._feed_vars = feed_list
        self._capacity = capacity
        self._stage = stage_on_device
        self._double_buffer = bool(use_double_buffer)
        self._sharding_fn = _as_sharding_fn(sharding)
        self._gen = None
        self._kind = None
        self._use_multiprocess = use_multiprocess
        self._num_workers = max(1, int(num_workers))

    # -- generator registration (reference reader.py:419-520) -----------
    def set_sample_generator(self, generator, batch_size, drop_last=True):
        def batcher():
            buf = []
            for sample in generator():
                buf.append(sample if isinstance(sample, (list, tuple))
                           else (sample,))
                if len(buf) == batch_size:
                    yield [np.stack([np.asarray(s[i]) for s in buf])
                           for i in range(len(buf[0]))]
                    buf = []
            if buf and not drop_last:
                yield [np.stack([np.asarray(s[i]) for s in buf])
                       for i in range(len(buf[0]))]

        self._gen = batcher
        return self

    def set_sample_list_generator(self, generator):
        def batcher():
            for samples in generator():
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(len(samples[0]))]

        self._gen = batcher
        return self

    def set_batch_generator(self, generator):
        self._gen = generator
        return self

    # -- iteration -------------------------------------------------------
    def _to_feed(self, batch):
        t0 = _time.perf_counter()
        items = ([batch[n] for n in self._feed_names]
                 if isinstance(batch, dict) else list(batch))
        arrays = []
        for name, a in zip(self._feed_names, items):
            # LoDTensors pass through whole; the executor decomposes them
            # into data + @LOD lengths itself
            if hasattr(a, "recursive_sequence_lengths"):
                arrays.append(a)
                continue
            a = np.asarray(a)
            if self._stage and self._double_buffer:
                import jax

                # async H2D with the program's feed sharding: stages
                # ahead (and pre-shards) while the step runs
                s = self._sharding_fn(name, a) \
                    if self._sharding_fn is not None else None
                a = jax.device_put(a, s) if s is not None \
                    else jax.device_put(a)
            arrays.append(a)
        _M_FEED_SECONDS.observe(_time.perf_counter() - t0)
        _M_BATCHES.inc()
        return dict(zip(self._feed_names, arrays))

    def _iter_threaded(self):
        stager = DeviceStager(self._gen(), transform=self._to_feed,
                              capacity=self._capacity, name="loader")
        try:
            for item in stager:
                yield item
        finally:
            # abandoning the loop (break / GC of the generator) must not
            # leak the non-daemon producer thread
            stager.close()

    def _iter_sync(self):
        """use_double_buffer=False: no thread, no queue, no device
        staging — each batch assembles on demand in the consumer."""
        for batch in self._gen():
            yield self._to_feed(batch)

    def _iter_multiprocess(self):
        """Worker processes run the generator and ship numpy batches over
        an mp queue; device staging stays in the parent (reference
        reader.py:73 _DataLoaderIterMultiProcess + shared-memory channel;
        fork + pickle is the TPU-host equivalent — parsing/augmentation
        escapes the GIL, the H2D stays on the process that owns the
        device client).

        Sharding: each worker runs the full generator and keeps batches
        round-robin by index — correct for any generator, but parse work
        multiplies by num_workers unless the generator shards itself via
        ``get_worker_info()`` (then every yielded batch is kept)."""
        import multiprocessing as mp
        import traceback

        ctx = mp.get_context("fork")
        q = ctx.Queue(maxsize=max(2, self._capacity))
        n = self._num_workers

        def pack(a):
            # LoDTensors must survive the queue with their lengths
            if hasattr(a, "recursive_sequence_lengths"):
                return ("__lod__", np.asarray(a),
                        a.recursive_sequence_lengths())
            return np.asarray(a)

        def worker(rank, gen, nworkers):
            global _worker_info
            _worker_info = WorkerInfo(rank, nworkers)
            try:
                for i, batch in enumerate(gen()):
                    if _worker_info.consumed_shard is False and \
                            i % nworkers != rank:
                        continue  # round-robin split of the batch stream
                    if isinstance(batch, dict):
                        items = [batch[k] for k in self._feed_names]
                    else:
                        items = list(batch)
                    q.put([pack(a) for a in items])
                q.put(None)
            except BaseException:  # forked worker: traceback shipped to the parent, re-raised there
                q.put(("__worker_error__", rank,
                       traceback.format_exc()))

        procs = [ctx.Process(target=worker, args=(r, self._gen, n),
                             daemon=True) for r in range(n)]
        for p in procs:
            p.start()

        def unpack(a):
            if isinstance(a, tuple) and len(a) == 3 and a[0] == "__lod__":
                from .lod import LoDTensor

                return LoDTensor(a[1], a[2])
            return a

        done = 0
        try:
            while done < n:
                item = q.get()
                if item is None:
                    done += 1
                    continue
                if isinstance(item, tuple) and item[0] == "__worker_error__":
                    raise RuntimeError(
                        "DataLoader worker %d died:\n%s"
                        % (item[1], item[2]))
                yield self._to_feed([unpack(a) for a in item])
        finally:
            for p in procs:
                p.terminate()
                p.join()

    def __iter__(self):
        if self._gen is None:
            raise RuntimeError("no generator set (set_batch_generator / "
                               "set_sample_generator / set_sample_list_generator)")
        if self._use_multiprocess:
            return self._iter_multiprocess()
        if not self._double_buffer:
            return self._iter_sync()
        return self._iter_threaded()


class DataLoader:
    """Reference ``reader.py:73``. ``from_generator`` is the supported
    path (``from_dataset`` arrives with the Dataset/trainer stack)."""

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False,
                       stage_on_device=True, use_multiprocess=False,
                       num_workers=2, sharding=None):
        """``use_double_buffer=True`` (default): a background
        ``DeviceStager`` thread prefetches up to ``capacity`` batches,
        each already assembled and — with ``stage_on_device=True`` —
        ``jax.device_put`` ahead of time (pass ``sharding=`` a
        ``CompiledProgram`` / dict / fn to pre-shard for GSPMD).
        ``use_double_buffer=False``: fully synchronous — no prefetch
        thread AND no ahead-of-time device staging (feeds reach the
        executor as host arrays and stage at dispatch); use it when
        batches are produced by something that must not run on a
        side thread, or to take H2D off the measurement."""
        if not feed_list:
            raise ValueError("feed_list is required")
        return GeneratorLoader(feed_list, capacity=capacity,
                               stage_on_device=stage_on_device,
                               use_multiprocess=use_multiprocess,
                               num_workers=num_workers,
                               use_double_buffer=use_double_buffer,
                               sharding=sharding)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset's batches as prefetched, device-staged feed
        dicts (reference ``reader.py:145``)."""
        loader = GeneratorLoader(dataset._use_vars)
        loader.set_batch_generator(dataset.batch_reader(drop_last))
        return loader


class PyReader:
    """Reference ``reader.py:583``: the older decorate_* API over the same
    machinery; ``start()``/``reset()`` are no-ops in iterable mode."""

    def __init__(self, feed_list=None, capacity=4, use_double_buffer=True,
                 iterable=True, return_list=False, sharding=None):
        self._loader = GeneratorLoader(feed_list, capacity,
                                       use_double_buffer=use_double_buffer,
                                       sharding=sharding)
        self._iterable = iterable

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        self._loader.set_sample_generator(sample_generator, batch_size,
                                          drop_last)

    def decorate_sample_list_generator(self, reader, places=None):
        self._loader.set_sample_list_generator(reader)

    def decorate_batch_generator(self, reader, places=None):
        self._loader.set_batch_generator(reader)

    def start(self):
        pass

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._loader)
