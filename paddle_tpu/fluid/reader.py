"""DataLoader / PyReader: the host->device input pipeline.

Parity: reference ``python/paddle/fluid/reader.py`` (``DataLoader:73``
``from_generator``, ``GeneratorLoader:298``, ``PyReader:583``) backed by
C++ ``LoDTensorBlockingQueue`` + ``buffered_reader`` (pre-H2D transfer on a
CUDA stream). TPU-native: a background thread assembles numpy batches and
stages them on device with ``jax.device_put`` ahead of consumption — the
double-buffer H2D overlap matters even more here because the chip can sit
behind a high-latency host link (see bench.py); the executor accepts the
staged ``jax.Array`` feeds untouched.
"""

import queue as _queue
import threading

import numpy as np

from .framework import Variable

__all__ = ["DataLoader", "PyReader", "GeneratorLoader"]


class GeneratorLoader:
    """Iterable loader: wraps a sample/batch generator into prefetched,
    device-staged feed dicts."""

    def __init__(self, feed_list, capacity=4, stage_on_device=True):
        self._feed_names = [v.name if isinstance(v, Variable) else str(v)
                            for v in feed_list]
        self._feed_vars = feed_list
        self._capacity = capacity
        self._stage = stage_on_device
        self._gen = None
        self._kind = None

    # -- generator registration (reference reader.py:419-520) -----------
    def set_sample_generator(self, generator, batch_size, drop_last=True):
        def batcher():
            buf = []
            for sample in generator():
                buf.append(sample if isinstance(sample, (list, tuple))
                           else (sample,))
                if len(buf) == batch_size:
                    yield [np.stack([np.asarray(s[i]) for s in buf])
                           for i in range(len(buf[0]))]
                    buf = []
            if buf and not drop_last:
                yield [np.stack([np.asarray(s[i]) for s in buf])
                       for i in range(len(buf[0]))]

        self._gen = batcher
        return self

    def set_sample_list_generator(self, generator):
        def batcher():
            for samples in generator():
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(len(samples[0]))]

        self._gen = batcher
        return self

    def set_batch_generator(self, generator):
        self._gen = generator
        return self

    # -- iteration -------------------------------------------------------
    def __iter__(self):
        if self._gen is None:
            raise RuntimeError("no generator set (set_batch_generator / "
                               "set_sample_generator / set_sample_list_generator)")
        end = object()
        q = _queue.Queue(maxsize=self._capacity)

        def produce():
            try:
                for batch in self._gen():
                    if isinstance(batch, dict):
                        arrays = [np.asarray(batch[n])
                                  for n in self._feed_names]
                    else:
                        arrays = [np.asarray(a) for a in batch]
                    if self._stage:
                        import jax

                        # async H2D: stages ahead while the step runs
                        arrays = [jax.device_put(a) for a in arrays]
                    q.put(dict(zip(self._feed_names, arrays)))
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            yield item


class DataLoader:
    """Reference ``reader.py:73``. ``from_generator`` is the supported
    path (``from_dataset`` arrives with the Dataset/trainer stack)."""

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False,
                       stage_on_device=True):
        if not feed_list:
            raise ValueError("feed_list is required")
        cap = capacity if use_double_buffer else 1
        return GeneratorLoader(feed_list, capacity=cap,
                               stage_on_device=stage_on_device)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        raise NotImplementedError(
            "from_dataset requires the Dataset trainer stack")


class PyReader:
    """Reference ``reader.py:583``: the older decorate_* API over the same
    machinery; ``start()``/``reset()`` are no-ops in iterable mode."""

    def __init__(self, feed_list=None, capacity=4, use_double_buffer=True,
                 iterable=True, return_list=False):
        self._loader = GeneratorLoader(feed_list, capacity)
        self._iterable = iterable

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        self._loader.set_sample_generator(sample_generator, batch_size,
                                          drop_last)

    def decorate_sample_list_generator(self, reader, places=None):
        self._loader.set_sample_list_generator(reader)

    def decorate_batch_generator(self, reader, places=None):
        self._loader.set_batch_generator(reader)

    def start(self):
        pass

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._loader)
