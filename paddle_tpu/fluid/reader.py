"""DataLoader / PyReader: the host->device input pipeline.

Parity: reference ``python/paddle/fluid/reader.py`` (``DataLoader:73``
``from_generator``, ``GeneratorLoader:298``, ``PyReader:583``) backed by
C++ ``LoDTensorBlockingQueue`` + ``buffered_reader`` (pre-H2D transfer on a
CUDA stream). TPU-native: a background thread assembles numpy batches and
stages them on device with ``jax.device_put`` ahead of consumption — the
double-buffer H2D overlap matters even more here because the chip can sit
behind a high-latency host link (see bench.py); the executor accepts the
staged ``jax.Array`` feeds untouched.
"""

import queue as _queue
import threading
import time as _time

import numpy as np

from . import monitor as _monitor
from .framework import Variable

__all__ = ["DataLoader", "PyReader", "GeneratorLoader", "WorkerInfo",
           "get_worker_info"]

# -- monitor series (process-wide; see fluid/monitor.py) ----------------------
_M_BATCHES = _monitor.counter(
    "reader_batches_total",
    help="batches produced by DataLoader/GeneratorLoader")
_M_STALLS = _monitor.counter(
    "reader_queue_full_total",
    help="producer stalls: the prefetch queue was full when a batch "
         "was ready (consumer is the bottleneck)")
_M_FEED_SECONDS = _monitor.histogram(
    "reader_feed_seconds",
    help="batch assembly + device staging time (_to_feed)")


class WorkerInfo:
    """Identity of the current DataLoader worker process. A generator
    that wants to avoid duplicate parsing shards its own input by
    ``get_worker_info()`` and then calls ``mark_sharded()`` so the loader
    keeps every batch it yields instead of round-robin filtering."""

    def __init__(self, rank, num_workers):
        self.id = rank
        self.num_workers = num_workers
        self.consumed_shard = False

    def mark_sharded(self):
        self.consumed_shard = True


_worker_info = None


def get_worker_info():
    """None in the main process; a WorkerInfo inside an mp worker."""
    return _worker_info


class GeneratorLoader:
    """Iterable loader: wraps a sample/batch generator into prefetched,
    device-staged feed dicts."""

    def __init__(self, feed_list, capacity=4, stage_on_device=True,
                 use_multiprocess=False, num_workers=2):
        self._feed_names = [v.name if isinstance(v, Variable) else str(v)
                            for v in feed_list]
        self._feed_vars = feed_list
        self._capacity = capacity
        self._stage = stage_on_device
        self._gen = None
        self._kind = None
        self._use_multiprocess = use_multiprocess
        self._num_workers = max(1, int(num_workers))

    # -- generator registration (reference reader.py:419-520) -----------
    def set_sample_generator(self, generator, batch_size, drop_last=True):
        def batcher():
            buf = []
            for sample in generator():
                buf.append(sample if isinstance(sample, (list, tuple))
                           else (sample,))
                if len(buf) == batch_size:
                    yield [np.stack([np.asarray(s[i]) for s in buf])
                           for i in range(len(buf[0]))]
                    buf = []
            if buf and not drop_last:
                yield [np.stack([np.asarray(s[i]) for s in buf])
                       for i in range(len(buf[0]))]

        self._gen = batcher
        return self

    def set_sample_list_generator(self, generator):
        def batcher():
            for samples in generator():
                yield [np.stack([np.asarray(s[i]) for s in samples])
                       for i in range(len(samples[0]))]

        self._gen = batcher
        return self

    def set_batch_generator(self, generator):
        self._gen = generator
        return self

    # -- iteration -------------------------------------------------------
    def _to_feed(self, batch):
        t0 = _time.perf_counter()
        items = ([batch[n] for n in self._feed_names]
                 if isinstance(batch, dict) else list(batch))
        arrays = []
        for a in items:
            # LoDTensors pass through whole; the executor decomposes them
            # into data + @LOD lengths itself
            if hasattr(a, "recursive_sequence_lengths"):
                arrays.append(a)
                continue
            a = np.asarray(a)
            if self._stage:
                import jax

                # async H2D: stages ahead while the step runs
                a = jax.device_put(a)
            arrays.append(a)
        _M_FEED_SECONDS.observe(_time.perf_counter() - t0)
        _M_BATCHES.inc()
        return dict(zip(self._feed_names, arrays))

    def _iter_threaded(self):
        end = object()
        q = _queue.Queue(maxsize=self._capacity)

        def produce():
            try:
                for batch in self._gen():
                    item = self._to_feed(batch)
                    try:
                        q.put_nowait(item)
                    except _queue.Full:
                        # consumer-bound: count the stall, then block
                        _M_STALLS.inc()
                        q.put(item)
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            yield item

    def _iter_multiprocess(self):
        """Worker processes run the generator and ship numpy batches over
        an mp queue; device staging stays in the parent (reference
        reader.py:73 _DataLoaderIterMultiProcess + shared-memory channel;
        fork + pickle is the TPU-host equivalent — parsing/augmentation
        escapes the GIL, the H2D stays on the process that owns the
        device client).

        Sharding: each worker runs the full generator and keeps batches
        round-robin by index — correct for any generator, but parse work
        multiplies by num_workers unless the generator shards itself via
        ``get_worker_info()`` (then every yielded batch is kept)."""
        import multiprocessing as mp
        import traceback

        ctx = mp.get_context("fork")
        q = ctx.Queue(maxsize=max(2, self._capacity))
        n = self._num_workers

        def pack(a):
            # LoDTensors must survive the queue with their lengths
            if hasattr(a, "recursive_sequence_lengths"):
                return ("__lod__", np.asarray(a),
                        a.recursive_sequence_lengths())
            return np.asarray(a)

        def worker(rank, gen, nworkers):
            global _worker_info
            _worker_info = WorkerInfo(rank, nworkers)
            try:
                for i, batch in enumerate(gen()):
                    if _worker_info.consumed_shard is False and \
                            i % nworkers != rank:
                        continue  # round-robin split of the batch stream
                    if isinstance(batch, dict):
                        items = [batch[k] for k in self._feed_names]
                    else:
                        items = list(batch)
                    q.put([pack(a) for a in items])
                q.put(None)
            except BaseException:
                q.put(("__worker_error__", rank,
                       traceback.format_exc()))

        procs = [ctx.Process(target=worker, args=(r, self._gen, n),
                             daemon=True) for r in range(n)]
        for p in procs:
            p.start()

        def unpack(a):
            if isinstance(a, tuple) and len(a) == 3 and a[0] == "__lod__":
                from .lod import LoDTensor

                return LoDTensor(a[1], a[2])
            return a

        done = 0
        try:
            while done < n:
                item = q.get()
                if item is None:
                    done += 1
                    continue
                if isinstance(item, tuple) and item[0] == "__worker_error__":
                    raise RuntimeError(
                        "DataLoader worker %d died:\n%s"
                        % (item[1], item[2]))
                yield self._to_feed([unpack(a) for a in item])
        finally:
            for p in procs:
                p.terminate()
                p.join()

    def __iter__(self):
        if self._gen is None:
            raise RuntimeError("no generator set (set_batch_generator / "
                               "set_sample_generator / set_sample_list_generator)")
        if self._use_multiprocess:
            return self._iter_multiprocess()
        return self._iter_threaded()


class DataLoader:
    """Reference ``reader.py:73``. ``from_generator`` is the supported
    path (``from_dataset`` arrives with the Dataset/trainer stack)."""

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False,
                       stage_on_device=True, use_multiprocess=False,
                       num_workers=2):
        if not feed_list:
            raise ValueError("feed_list is required")
        cap = capacity if use_double_buffer else 1
        return GeneratorLoader(feed_list, capacity=cap,
                               stage_on_device=stage_on_device,
                               use_multiprocess=use_multiprocess,
                               num_workers=num_workers)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset's batches as prefetched, device-staged feed
        dicts (reference ``reader.py:145``)."""
        loader = GeneratorLoader(dataset._use_vars)
        loader.set_batch_generator(dataset.batch_reader(drop_last))
        return loader


class PyReader:
    """Reference ``reader.py:583``: the older decorate_* API over the same
    machinery; ``start()``/``reset()`` are no-ops in iterable mode."""

    def __init__(self, feed_list=None, capacity=4, use_double_buffer=True,
                 iterable=True, return_list=False):
        self._loader = GeneratorLoader(feed_list, capacity)
        self._iterable = iterable

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        self._loader.set_sample_generator(sample_generator, batch_size,
                                          drop_last)

    def decorate_sample_list_generator(self, reader, places=None):
        self._loader.set_sample_list_generator(reader)

    def decorate_batch_generator(self, reader, places=None):
        self._loader.set_batch_generator(reader)

    def start(self):
        pass

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._loader)
