"""Optimizers: append update ops onto the program.

Parity: reference ``python/paddle/fluid/optimizer.py:54`` — ``minimize`` =
``append_backward`` + ``apply_gradients``; accumulators are persistable scope
vars; LR is a graph var (scheduler output or constant). 13 concrete
optimizers + wrappers (ModelAverage, EMA, Lookahead, Recompute).

All update math executes inside the single compiled train step with donated
buffers — an optimizer step costs zero extra memory traffic beyond the
reads/writes themselves.
"""

import numpy as np

from . import framework, unique_name
from .backward import append_backward
from .framework import Variable, default_main_program, default_startup_program
from .initializer import Constant
from .layer_helper import LayerHelper

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "Dpsgd", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "Lamb",
    "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "DpsgdOptimizer", "DecayedAdagradOptimizer",
    "AdadeltaOptimizer", "RMSPropOptimizer", "FtrlOptimizer", "LambOptimizer",
    "LarsMomentumOptimizer", "DGCMomentumOptimizer",
    "ModelAverage", "ExponentialMovingAverage", "LookaheadOptimizer",
    "RecomputeOptimizer", "PipelineOptimizer", "GradientMergeOptimizer",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None,
                 grad_clip=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._grad_clip = grad_clip
        self._accumulators = {}  # acc_name -> {param_name: var}
        self._lr_var = None
        self.type = self.__class__.__name__.replace("Optimizer", "").lower()
        # dygraph (eager) optimizer state: per-param accumulators, their
        # names for state_dict keys, and checkpoint state restored by
        # set_dict awaiting first allocation
        self._eager_state = {}
        self._eager_names = {}
        self._loaded_state = {}

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is not None:
            return
        helper = LayerHelper("learning_rate")
        name = unique_name.generate("learning_rate")
        self._lr_var = helper.main_program.global_block().create_var(
            name=name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True,
        )
        sb = helper.startup_program.global_block()
        sv = sb.create_var(name=name, shape=(1,), dtype="float32", persistable=True)
        Constant(float(self._learning_rate))(sv, sb)

    def _global_learning_rate(self):
        return self._lr_var

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper("accum")
        shape = shape if shape is not None else param.shape
        dtype = dtype or param.dtype
        var_name = unique_name.generate("%s_%s" % (param.name, name))
        var = helper.main_program.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        # moments share the param's TP layout so the optimizer update is
        # local to each shard (no resharding per step)
        pspec = getattr(param, "shard_spec", None)
        if pspec is not None and tuple(shape) == tuple(param.shape):
            var.shard_spec = pspec
        sb = helper.startup_program.global_block()
        sv = sb.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True)
        Constant(float(fill_value))(sv, sb)
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- the template -------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        block = default_main_program().global_block()
        self._create_global_learning_rate()

        # grad clipping (reference clip.py append_gradient_clip_ops)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        else:
            from .clip import append_gradient_clip_ops

            params_grads = append_gradient_clip_ops(params_grads)

        # weight decay / regularization (reference regularizer.append_regularization_ops)
        from .regularizer import append_regularization_ops

        params_grads = append_regularization_ops(params_grads, self.regularization)

        self._create_accumulators(block, [p for p, _ in params_grads])
        for pg in params_grads:
            self._append_optimize_op(block, pg)
        self._finish_update(block, params_grads)
        return params_grads

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        if framework.in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list,
                                          grad_clip=grad_clip)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- dygraph (eager) path ----------------------------------------------
    def _dygraph_minimize(self, loss, parameter_list, grad_clip=None):
        """Eager update: runs loss.backward() if grads are absent, applies
        the optional ``grad_clip`` strategy (dygraph_grad_clip.py, the
        reference's optimizer.py:680 hook), then the optimizer op eagerly
        per param (reference dygraph minimize)."""
        from .dygraph.base import VarBase

        tracer = framework._dygraph_tracer()
        if parameter_list is None:
            raise ValueError("dygraph minimize needs parameter_list "
                             "(e.g. model.parameters())")
        if tracer._tape:
            loss.backward()
        import jax.numpy as jnp

        if isinstance(self._learning_rate, VarBase):
            lr = float(self._learning_rate.numpy().reshape(-1)[0])
        elif callable(self._learning_rate):
            lr = float(self._learning_rate())
        else:
            lr = float(self._learning_rate)
        params_grads = []
        with tracer._no_grad_guard():
            for p in parameter_list:
                if p is None or p._grad is None or p.stop_gradient:
                    continue
                params_grads.append((p, p._grad))
            # clip RAW grads, then fold regularization in — the static
            # path's apply_gradients order (clip ops before
            # append_regularization_ops), so both modes update identically.
            # The call-site grad_clip wins over the constructor-level one.
            clip = grad_clip if grad_clip is not None else self._grad_clip
            if clip is not None:
                params_grads = clip(params_grads)
            regularized = []
            for p, g in params_grads:
                if getattr(p, "regularizer", None) is not None or \
                        self.regularization is not None:
                    reg = getattr(p, "regularizer", None) or self.regularization
                    from .regularizer import L1DecayRegularizer

                    if isinstance(reg, L1DecayRegularizer):
                        g = g + reg._coeff * jnp.sign(p._ivar)
                    else:
                        g = g + reg._coeff * p._ivar
                regularized.append((p, g))
            params_grads = regularized
            for p, g in params_grads:
                p._ivar = self._eager_update(p, g, lr)
        return None, params_grads

    def _eager_state_for(self, p, names_and_init):
        import jax.numpy as jnp

        st = self._eager_state.get(id(p))
        if st is None:
            st = {}
            pending = self._loaded_state
            for name, init in names_and_init:
                key = "%s@%s" % (p.name, name)
                if key in pending:          # set_dict restore, by name
                    st[name] = jnp.asarray(pending.pop(key))
                elif np.isscalar(init):
                    st[name] = jnp.full((1,), init, dtype=p._ivar.dtype)
                else:
                    st[name] = jnp.full(p._ivar.shape, 0.0, dtype=p._ivar.dtype)
            self._eager_state[id(p)] = st
            self._eager_names[id(p)] = p.name
        return st

    # -- dygraph checkpointing (reference optimizer.py:100 state_dict /
    # :131 set_dict): eager accumulators keyed "<param>@<slot>", plus
    # global_step when the LR is a LearningRateDecay object ------------
    def state_dict(self):
        if not framework.in_dygraph_mode():
            raise RuntimeError(
                "optimizer.state_dict() is dygraph-only; static graph "
                "optimizer state lives in scope persistables "
                "(fluid.io.save)")
        # still-pending restored state (set_dict before any minimize)
        # must survive a re-save — it simply hasn't allocated yet
        out = dict(self._loaded_state)
        names = self._eager_names
        for pid, st in self._eager_state.items():
            for slot, arr in st.items():
                out["%s@%s" % (names[pid], slot)] = np.asarray(arr)
        from .dygraph.learning_rate_scheduler import LearningRateDecay

        if isinstance(self._learning_rate, LearningRateDecay):
            out["global_step"] = np.asarray(
                [self._learning_rate.step_num], np.int64)
        return out

    def set_dict(self, state_dict):
        """Restore from ``state_dict``. Accumulators apply lazily by
        param NAME at first use (eager state allocates on first
        minimize); global_step steps the LR decay object now."""
        state = dict(state_dict)
        gs = state.pop("global_step", None)
        if gs is not None:
            from .dygraph.learning_rate_scheduler import LearningRateDecay

            if isinstance(self._learning_rate, LearningRateDecay):
                self._learning_rate.step_num = int(
                    np.asarray(gs).ravel()[0])
            else:
                import logging

                logging.getLogger(__name__).warning(
                    "set_dict: checkpoint carries global_step=%d but "
                    "this optimizer's learning_rate is not a "
                    "LearningRateDecay object — the schedule position "
                    "is dropped", int(np.asarray(gs).ravel()[0]))
        self._loaded_state = state
        # already-allocated eager state updates in place
        names = self._eager_names
        for pid, st in self._eager_state.items():
            for slot in list(st):
                key = "%s@%s" % (names[pid], slot)
                if key in self._loaded_state:
                    import jax.numpy as jnp

                    st[slot] = jnp.asarray(self._loaded_state.pop(key))

    set_state_dict = set_dict

    def _eager_update(self, p, g, lr):
        raise NotImplementedError(
            "%s has no eager update; use static graph mode" % type(self).__name__)

    def _lr_for(self, param):
        """Per-param LR multiplier (param.optimize_attr['learning_rate'])."""
        mult = 1.0
        if hasattr(param, "optimize_attr"):
            mult = param.optimize_attr.get("learning_rate", 1.0)
        if mult == 1.0:
            return self._lr_var
        from .layers import nn

        return nn.scale(self._lr_var, scale=mult)


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)

    def _eager_update(self, p, g, lr):
        return p._ivar - lr * g

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param]},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _eager_update(self, p, g, lr):
        st = self._eager_state_for(p, [("velocity", None)])
        v_new = self._momentum * st["velocity"] + g
        st["velocity"] = v_new
        if self._use_nesterov:
            return p._ivar - (g + self._momentum * v_new) * lr
        return p._ivar - lr * v_new

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            "momentum",
            inputs={"Param": [param], "Grad": [grad], "Velocity": [velocity],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": [param], "Grad": [grad], "Velocity": [velocity],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            "adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None, lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _eager_update(self, p, g, lr):
        import jax.numpy as jnp

        st = self._eager_state_for(
            p, [("m", None), ("v", None), ("b1p", self._beta1),
                ("b2p", self._beta2)])
        st["m"] = self._beta1 * st["m"] + (1 - self._beta1) * g
        st["v"] = self._beta2 * st["v"] + (1 - self._beta2) * jnp.square(g)
        b1p, b2p = st["b1p"].reshape(()), st["b2p"].reshape(())
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        new_p = p._ivar - lr_t * st["m"] / (jnp.sqrt(st["v"]) + self._epsilon)
        st["b1p"] = st["b1p"] * self._beta1
        st["b2p"] = st["b2p"] * self._beta2
        return new_p

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=(1,))
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=(1,))

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            "adam",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=(1,))

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "adamax",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [self._get_accumulator("moment", param)],
                    "InfNorm": [self._get_accumulator("inf_norm", param)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", param)],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param],
                     "MomentOut": [self._get_accumulator("moment", param)],
                     "InfNormOut": [self._get_accumulator("inf_norm", param)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", param)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0):
        super().__init__(learning_rate)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "dpsgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param]},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma},
        )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        g = self._get_accumulator("__avg_squared_grad", param)
        u = self._get_accumulator("__avg_squared_update", param)
        return block.append_op(
            "adadelta",
            inputs={"Param": [param], "Grad": [grad], "AvgSquaredGrad": [g],
                    "AvgSquaredUpdate": [u],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [g],
                     "AvgSquaredUpdateOut": [u]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "rmsprop",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [self._get_accumulator("momentum", param)],
                    "MeanSquare": [self._get_accumulator("mean_square", param)],
                    "MeanGrad": [self._get_accumulator("mean_grad", param)],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param],
                     "MomentOut": [self._get_accumulator("momentum", param)],
                     "MeanSquareOut": [self._get_accumulator("mean_square", param)],
                     "MeanGradOut": [self._get_accumulator("mean_grad", param)]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return block.append_op(
            "ftrl",
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, regularization=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization,
                         name)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(param):
            wd = 0.0
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            "lamb",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd},
        )


class DGCMomentumOptimizer(MomentumOptimizer):
    """Momentum with deep-gradient-compression top-k sparsification
    (reference ``optimizer.py:870``, ``operators/dgc_op.cc``): each step the
    ``dgc`` op applies momentum correction + error-feedback accumulation and
    emits a masked-dense gradient with only the top ``1-sparsity`` fraction
    of entries non-zero (paddle_tpu/parallel/dgc.py); the param update is a
    plain SGD step on that compressed gradient. Under ``GradAllReduce`` the
    allreduce moves onto the compressed gradient (the reference's
    sparse_all_reduce_op_handle). Steps before ``rampup_begin_step`` behave
    as plain momentum, gated in-graph on a step counter."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, name=None):
        super().__init__(learning_rate, momentum, use_nesterov, regularization,
                         name)
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = int(rampup_step)
        self._sparsity = list(sparsity)
        self._dgc_step_var = None

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        u = self._get_accumulator("dgc_u", param)
        v = self._get_accumulator("dgc_v", param)
        inputs = {"U": [u], "V": [v], "Grad": [grad]}
        if self._rampup_begin_step > 0 or len(self._sparsity) > 1:
            if self._dgc_step_var is None:
                from .layers import nn

                self._dgc_step_var = nn.autoincreased_step_counter(
                    counter_name="@DGC_STEP@", begin=0)
            inputs["CurrentStep"] = [self._dgc_step_var]
        compressed = block.create_var(
            name=unique_name.generate(grad.name + ".dgc"), shape=grad.shape,
            dtype=grad.dtype, stop_gradient=True)
        block.append_op(
            "dgc", inputs=inputs,
            outputs={"UOut": [u], "VOut": [v], "GradOut": [compressed]},
            attrs={"m": self._momentum,
                   "sparsity": [float(s) for s in self._sparsity],
                   "rampup_begin_step": self._rampup_begin_step,
                   "rampup_step": self._rampup_step})
        return block.append_op(
            "sgd",
            inputs={"Param": [param], "Grad": [compressed],
                    "LearningRate": [self._lr_for(param)]},
            outputs={"ParamOut": [param]})


# -- wrappers ----------------------------------------------------------------


class ModelAverage(Optimizer):
    """Maintains WINDOWED running averages of params; ``apply()`` swaps them
    in for eval (reference ``optimizer.py:2512`` +
    ``operators/average_accumulates_op.cc``).

    Window semantics: accumulation restarts whenever the in-window count
    reaches ``clip(average_window_rate * num_updates, min_average_window,
    max_average_window)``; the just-closed window is kept so the served
    average always covers (current + previous) windows — a bounded window,
    not an unbounded running sum. The restart is gated in-graph (no
    divergent control flow under jit):

        r        = (num_acc + 1 >= W)            # restart gate, 0/1
        sum_prev' = r * (sum + p) + (1-r) * sum_prev
        old_num'  = r * (num_acc + 1) + (1-r) * old_num
        sum'      = (1-r) * (sum + p)
        num_acc'  = (1-r) * (num_acc + 1)
        average   = (sum + sum_prev) / (num_acc + old_num)
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_sums = {}
        program = default_main_program()
        block = program.global_block()
        from .layers import nn, tensor

        steps = nn.autoincreased_step_counter(
            counter_name="@MODEL_AVERAGE_STEP@", begin=1)
        stepsf = tensor.cast(steps, "float32")
        # W = clip(rate * num_updates, min_window, max_window)
        w = nn.clip(nn.scale(stepsf, scale=float(average_window_rate)),
                    float(min_average_window), float(max_average_window))
        for param in program.all_parameters():
            if not param.trainable:
                continue
            s = self._add_accumulator("sum", param)
            sp = self._add_accumulator("sum_prev", param)
            n = self._add_accumulator("num_acc", param, shape=(1,))
            on = self._add_accumulator("old_num_acc", param, shape=(1,))
            n1 = nn.scale(n, scale=1.0, bias=1.0)          # num_acc + 1
            s1 = nn.elementwise_add(s, param)              # sum + p
            rb = n1 >= w
            r = tensor.cast(rb, "float32")                 # restart gate
            keep = nn.scale(r, scale=-1.0, bias=1.0)       # 1 - r
            new_sp = nn.elementwise_add(
                nn.elementwise_mul(s1, r, axis=-1),
                nn.elementwise_mul(sp, keep, axis=-1))
            new_on = nn.elementwise_add(
                nn.elementwise_mul(n1, r), nn.elementwise_mul(on, keep))
            new_s = nn.elementwise_mul(s1, keep, axis=-1)
            new_n = nn.elementwise_mul(n1, keep)
            for src, dst in ((new_sp, sp), (new_on, on), (new_s, s),
                             (new_n, n)):
                block.append_op("assign", inputs={"X": [src]},
                                outputs={"Out": [dst]})
            self.params_sums[param.name] = (s, sp, n, on)

    import contextlib

    @contextlib.contextmanager
    def apply(self, executor, need_restore=True):
        from .executor import global_scope

        scope = global_scope()
        backups = {}
        for pname, (s, sp, n, on) in self.params_sums.items():
            backups[pname] = scope.find_var(pname)
            ssum = (np.asarray(scope.find_var(s.name))
                    + np.asarray(scope.find_var(sp.name)))
            num = float(np.asarray(scope.find_var(n.name)).reshape(-1)[0]
                        + np.asarray(scope.find_var(on.name)).reshape(-1)[0])
            if num > 0:
                scope.set_var(pname, (ssum / num).astype(backups[pname].dtype))
        try:
            yield
        finally:
            if need_restore:
                for pname, val in backups.items():
                    scope.set_var(pname, val)

    def restore(self, executor):
        pass


class ExponentialMovingAverage:
    """EMA of params updated in-graph (reference ``optimizer.py:2814``)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._ema_vars = {}
        program = default_main_program()
        block = program.global_block()
        helper = LayerHelper("ema")
        for param in program.all_parameters():
            if not param.trainable:
                continue
            name = unique_name.generate(param.name + ".ema")
            ema = block.create_var(name=name, shape=param.shape, dtype=param.dtype,
                                   persistable=True, stop_gradient=True)
            sb = default_startup_program().global_block()
            sv = sb.create_var(name=name, shape=param.shape, dtype=param.dtype,
                               persistable=True)
            Constant(0.0)(sv, sb)
            self._ema_vars[param.name] = ema
            # ema = decay*ema + (1-decay)*param, written each step
            from .layers import nn

            tmp = nn.elementwise_add(
                nn.scale(ema, scale=self._decay),
                nn.scale(param, scale=1.0 - self._decay),
            )
            block.append_op("assign", inputs={"X": [tmp]}, outputs={"Out": [ema]})

    import contextlib

    @contextlib.contextmanager
    def apply(self, executor, need_restore=True):
        from .executor import global_scope

        scope = global_scope()
        backups = {}
        for pname, ema in self._ema_vars.items():
            backups[pname] = scope.find_var(pname)
            v = scope.find_var(ema.name)
            if v is not None:
                scope.set_var(pname, v)
        try:
            yield
        finally:
            if need_restore:
                for pname, val in backups.items():
                    scope.set_var(pname, val)

    def update(self):
        pass  # updates happen in-graph

    def restore(self, executor):
        pass


class LookaheadOptimizer:
    """Reference ``optimizer.py:3634``: slow/fast weights; every k steps slow
    += alpha*(fast-slow), fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        opt_ops, params_grads = self.inner_optimizer.minimize(loss,
                                                              startup_program)
        program = loss.block.program
        block = program.global_block()
        helper = LayerHelper("lookahead")
        from .layers import nn, tensor

        step = nn.autoincreased_step_counter(counter_name="@LOOKAHEAD_STEP@")
        stepf = tensor.cast(step, "float32")
        kf = float(self.k)
        # sync_flag = 1.0 when step % k == 0
        mod = nn.elementwise_sub(
            stepf, nn.scale(nn.elementwise_floordiv(
                tensor.cast(step, "int64"),
                tensor.fill_constant([1], "int64", self.k)).astype("float32"),
                scale=kf))
        is_sync = tensor.cast(mod < 0.5, "float32")
        for param, _ in params_grads:
            name = unique_name.generate(param.name + ".slow")
            slow = block.create_var(name=name, shape=param.shape,
                                    dtype=param.dtype, persistable=True,
                                    stop_gradient=True)
            sb = default_startup_program().global_block()
            sv = sb.create_var(name=name, shape=param.shape, dtype=param.dtype,
                               persistable=True)
            Constant(0.0)(sv, sb)
            new_slow = nn.elementwise_add(
                slow, nn.scale(nn.elementwise_sub(param, slow),
                               scale=self.alpha))
            merged_slow = nn.elementwise_add(
                nn.elementwise_mul(is_sync, new_slow),
                nn.elementwise_mul(nn.scale(is_sync, scale=-1.0, bias=1.0), slow),
            )
            merged_fast = nn.elementwise_add(
                nn.elementwise_mul(is_sync, merged_slow),
                nn.elementwise_mul(nn.scale(is_sync, scale=-1.0, bias=1.0), param),
            )
            block.append_op("assign", inputs={"X": [merged_slow]},
                            outputs={"Out": [slow]})
            block.append_op("assign", inputs={"X": [merged_fast]},
                            outputs={"Out": [param]})
        return opt_ops, params_grads


class RecomputeOptimizer:
    """Activation recomputation (reference ``optimizer.py:3341``). Under the
    functional-autodiff design the checkpoint list is carried on the autodiff
    op; its lowering wraps forward segments in ``jax.checkpoint`` so XLA
    rematerializes instead of saving activations."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None, checkpoints=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               checkpoints=self._checkpoints or checkpoints)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        return self.apply_gradients(params_grads), params_grads


class PipelineOptimizer:
    """Pipeline parallelism (reference ``optimizer.py:3048``). Records the
    cut points on the program; ``CompiledProgram.with_pipeline`` consumes
    them to run the forward as a GPipe schedule over the 'pp' mesh axis
    (stages dispatched by lax.switch, activations via ppermute — see
    ``compiler.py:_wrap_step_pipeline`` and paddle_tpu/parallel/pipeline.py).
    ``place_list``/``concurrency_list``/``queue_size`` are the reference's
    host-thread knobs and are meaningless in the single-SPMD-program design;
    accepted for API parity, ignored."""

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0):
        self._optimizer = optimizer
        self._cut_list = cut_list or []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        program = loss.block.program
        program._pipeline_cut_vars = [
            [v.name for v in cut] if isinstance(cut, (list, tuple)) else [cut.name]
            for cut in self._cut_list
        ]
        return result


class GradientMergeOptimizer:
    """Gradient accumulation / batch merge (capability of the reference's
    ``ir/multi_batch_merge_pass.cc``: replicate forward/backward, merge
    grads, apply once per k micro-batches).

    TPU-first redesign: instead of cloning the graph k times, grads
    accumulate into persistable buffers every step and the inner
    optimizer's *entire* update subgraph is gated arithmetically —
    its writes to persistable state (params, moments, LR counters) are
    SSA-renamed to shadows and committed via
    ``state' = state + sync * (shadow - state)`` where
    ``sync = (step % k == 0)``. One static XLA graph, no divergent
    control flow, momentum/Adam state advances exactly once per merge —
    bit-matching a plain optimizer fed the k-step mean gradient.
    """

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self.inner_optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set, callbacks)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self.k_steps <= 1:
            return self.inner_optimizer.minimize(
                loss, startup_program, parameter_list, no_grad_set)
        from .framework import program_guard
        from .layers import nn, tensor

        main = loss.block.program
        startup = startup_program or default_startup_program()
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        block = main.global_block()
        with program_guard(main, startup):
            # per-instance counter: two merge-wrapped optimizers in one
            # program (e.g. GAN G/D) must not share or double-increment it
            step = nn.autoincreased_step_counter(
                counter_name=unique_name.generate("@GRADMERGE_STEP@"),
                begin=1)
            from .layers.control_flow import equal

            k = tensor.fill_constant([1], "int64", self.k_steps)
            # sync == 1.0 on steps k, 2k, ...
            sync = tensor.cast(
                equal(nn.elementwise_mod(step, k),
                      tensor.zeros([1], "int64")), "float32")

            # accumulate: acc_new = acc + g; merged grad = acc_new / k
            acc_pairs = []  # (acc var, acc_new var)
            merged = []
            for p, g in params_grads:
                # unique per instance: a param shared by two merge-wrapped
                # optimizers must not alias one accumulator
                acc = tensor.create_global_var(
                    shape=list(p.shape), value=0.0, dtype=p.dtype,
                    persistable=True,
                    name=unique_name.generate(p.name + "@GRAD@MERGE"))
                acc_new = nn.elementwise_add(acc, g)
                gm = (nn.scale(acc_new, scale=1.0 / self.k_steps)
                      if self.avg else acc_new)
                acc_pairs.append((acc, acc_new))
                merged.append((p, gm))

            # inner optimizer appends its update ops; record the range
            start_idx = len(block.ops)
            optimize_ops = self.inner_optimizer.apply_gradients(merged)
            end_idx = len(block.ops)
            shadows = self._shadow_persistable_writes(block, start_idx,
                                                      end_idx)
            # commit gated state: state' = state + sync * (shadow - state)
            for orig_name, shadow_name in shadows.items():
                orig = block.var(orig_name)
                shadow = block.var(shadow_name)
                gate = tensor.cast(sync, orig.dtype)
                delta = nn.elementwise_mul(
                    nn.elementwise_sub(shadow, orig), gate, axis=-1)
                tensor.assign(nn.elementwise_add(orig, delta), output=orig)
            # reset accumulators on sync: acc = acc_new * (1 - sync)
            keep = nn.elementwise_sub(tensor.ones([1], "float32"), sync)
            for acc, acc_new in acc_pairs:
                gate = tensor.cast(keep, acc.dtype)
                tensor.assign(nn.elementwise_mul(acc_new, gate, axis=-1),
                              output=acc)
        return optimize_ops, params_grads

    @staticmethod
    def _shadow_persistable_writes(block, start_idx, end_idx):
        """SSA-rename persistable outputs of ops[start:end] to fresh
        non-persistable shadow vars; later reads inside the range follow
        the rename. Returns {original_name: final_shadow_name}."""
        latest = {}
        n_shadow = 0
        for op in block.ops[start_idx:end_idx]:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [latest.get(n, n) for n in names]
            for slot, names in op.outputs.items():
                new_names = []
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is not None and getattr(v, "persistable", False):
                        shadow = "%s@GM_SHADOW_%d" % (n, n_shadow)
                        n_shadow += 1
                        block.create_var(name=shadow, shape=v.shape,
                                         dtype=v.dtype, stop_gradient=True)
                        latest[n] = shadow
                        new_names.append(shadow)
                    else:
                        new_names.append(n)
                op.outputs[slot] = new_names
        return latest


SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Dpsgd = DpsgdOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
