"""Async communicator for parameter-server training.

Parity: reference ``fluid/communicator.py`` (``Communicator:26`` —
start/stop/is_running over the C++ async communicator,
``operators/distributed/communicator.h:175``). There the communicator
owns background merge-and-send threads so trainer iterations never
block on parameter-server RPCs; here the same role is played by
``distributed.ps.AsyncPusher`` threads: ``start()`` interposes an
async-pushing proxy in front of every distributed table the program
uses (pulls stay synchronous — the device graph needs the rows), and
``stop()`` drains the queues and restores direct tables. Used inside
the fleet parameter-server path the same way the reference uses it.
"""

from . import framework

__all__ = ["Communicator"]


class _TableProxy(object):
    """Attribute-forwarding view over a registered table; subclasses
    override the communication entry points."""

    def __init__(self, table):
        self._table = table

    def __getattr__(self, name):
        # only fires for names not on the proxy: everything else (vocab,
        # dim, dump, load, ...) serves from the wrapped table
        return getattr(self.__dict__["_table"], name)


class _AsyncTableProxy(_TableProxy):
    """``push`` queues onto the background pusher thread (async-SGD
    staleness model); everything else is direct."""

    def __init__(self, table, pusher):
        super().__init__(table)
        self._pusher = pusher

    def push(self, ids, grads, **kw):
        self._pusher.push(ids, grads, **kw)


class _GeoTableProxy(_TableProxy):
    """Geo-SGD table view (reference GeoSgdCommunicator,
    ``communicator.h:332``): pulls serve the worker's LOCAL mirror, pushes
    apply SGD on the mirror only; every ``k_steps`` pushes the
    accumulated delta ships to the global table through
    ``GeoCommunicator.maybe_sync`` and the mirror rebases."""

    def __init__(self, table, comm):
        super().__init__(table)
        self._comm = comm

    def pull(self, ids):
        import numpy as np

        # same contract as EmbeddingTable.pull (ps.py): flatten to 1-D,
        # always return (N, dim), reject out-of-range ids loudly
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        local = self._comm.local
        if ids.size and (ids.min() < 0 or ids.max() >= local.shape[0]):
            raise IndexError("id out of range for vocab %d" % local.shape[0])
        return local[ids].copy()

    def push(self, ids, grads, lr=0.01, optimizer="sgd", **kw):
        import numpy as np

        if optimizer != "sgd":
            # Geo-SGD is SGD-by-construction: the shipped quantity is a
            # parameter DELTA, which only equals an optimizer step for
            # plain SGD. Refuse rather than silently change update math.
            raise ValueError(
                "geo communication supports optimizer='sgd' only, got %r "
                "(reference GeoSgdCommunicator has the same constraint)"
                % (optimizer,))
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        local = self._comm.local
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0],
                                                      local.shape[1])
        if ids.size and (ids.min() < 0 or ids.max() >= local.shape[0]):
            raise IndexError("id out of range for vocab %d" % local.shape[0])
        # duplicate ids must accumulate, like the table's own sgd apply
        np.subtract.at(local, ids, float(lr) * grads)
        self._comm.maybe_sync()


class Communicator(object):
    def __init__(self, program=None, vars_info=None, trainers=None,
                 geo_sgd_need_push_nums=None):
        """``program`` is the transpiled trainer program; its
        ``distributed_lookup_table`` ops name the tables to communicate.
        ``vars_info``/``trainers``/``geo_sgd_need_push_nums`` are the
        reference's geo-SGD knobs: when all three are given, tables are
        synced through ``distributed.ps.GeoCommunicator`` cadence
        instead of per-push queues."""
        program = program or framework.default_main_program()
        names = []
        for blk in program.blocks:
            for op in blk.ops:
                if op.type == "distributed_lookup_table":
                    name = op.attr("table_name")
                    if name not in names:
                        names.append(name)
        self._names = names
        self._geo = bool(vars_info and trainers and geo_sgd_need_push_nums)
        self._geo_k = int(geo_sgd_need_push_nums) if self._geo else 0
        self._running = False
        self._pushers = {}
        self._geo_comms = {}
        self._originals = {}

    def start(self):
        """Interpose async pushers (or geo communicators) in front of the
        program's tables. Idempotent while running."""
        if self._running:
            return
        from ..distributed import ps

        # resolve every table BEFORE interposing any proxy: an unknown
        # name raises here with the registry untouched, so a failed
        # start() never leaves a half-proxied registry behind
        tables = {name: ps.get_table(name) for name in self._names}
        for name, table in tables.items():
            self._originals[name] = table
            if self._geo:
                comm = ps.GeoCommunicator(table, k_steps=self._geo_k)
                self._geo_comms[name] = comm
                ps.register_table(name, _GeoTableProxy(table, comm))
            else:
                pusher = ps.AsyncPusher(table)
                self._pushers[name] = pusher
                ps.register_table(name, _AsyncTableProxy(table, pusher))
        self._running = True

    def stop(self):
        """Drain queued pushes / force a final geo sync, then restore the
        direct tables."""
        if not self._running:
            return
        from ..distributed import ps

        # every table must be restored even when a drain re-raises a
        # deferred push error — record the first error, finish the
        # restores, then surface it
        first_exc = None
        for name, pusher in self._pushers.items():
            try:
                pusher.stop()
            except Exception as e:
                if first_exc is None:
                    first_exc = e
            ps.register_table(name, self._originals[name])
        for name, comm in self._geo_comms.items():
            try:
                comm.maybe_sync(force=True)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
            ps.register_table(name, self._originals[name])
        self._pushers.clear()
        self._geo_comms.clear()
        self._originals.clear()
        self._running = False
        if first_exc is not None:
            raise first_exc

    def is_running(self):
        return self._running
