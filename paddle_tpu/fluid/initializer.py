"""Parameter initializers — append init ops to the startup program.

Parity: reference ``python/paddle/fluid/initializer.py`` (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray).
"""

import math

import numpy as np

from . import framework

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "NumpyArrayInitializer",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _fan_in_out(self, var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0] if shape else 1,) * 2
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            "fill_constant",
            outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": framework.dtype_str(var.dtype),
                   "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random",
            outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": framework.dtype_str(var.dtype),
                   "min": float(self.low), "max": float(self.high), "seed": self.seed},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random",
            outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": framework.dtype_str(var.dtype),
                   "mean": float(self.loc), "std": float(self.scale), "seed": self.seed},
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": framework.dtype_str(var.dtype),
                   "mean": float(self.loc), "std": float(self.scale), "seed": self.seed},
        )


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.fan_out = fan_out
        self.seed = seed

    def __call__(self, var, block):
        fan_in, fan_out = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        fan_out = self.fan_out if self.fan_out is not None else fan_out
        if self.uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        fan_in, _ = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fan_in)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose (reference
    ``initializer.py`` BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear init needs a 4-D filter")
        c, k, h, w = shape
        f = np.ceil(w / 2.0)
        cc = (2 * f - 1 - f % 2) / (2.0 * f)
        grid = np.ogrid[:h, :w]
        weight = (1 - abs(grid[0] / f - cc)) * (1 - abs(grid[1] / f - cc))
        full = np.zeros(shape, dtype=np.float32)
        for i in range(c):
            for j in range(k):
                full[i, j] = weight
        NumpyArrayInitializer(full)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            "assign_value",
            outputs={"Out": var},
            attrs={
                "shape": list(self.value.shape),
                "dtype": framework.dtype_str(var.dtype),
                "values": self.value.astype(var.dtype).ravel().tolist(),
            },
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
