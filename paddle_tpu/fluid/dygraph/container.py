"""Layer containers (reference ``dygraph/container.py:20``)."""

from .layers import Layer

__all__ = ["Sequential"]


class Sequential(Layer):
    """Chains sub-layers in construction order: ``Sequential(l1, l2)``
    or ``Sequential(("a", l1), ("b", l2))``. The reference requires a
    leading ``name_scope`` string; it is accepted optionally here (the
    2.x signature dropped it)."""

    def __init__(self, *layers):
        name_scope = None
        if layers and isinstance(layers[0], str):
            name_scope, layers = layers[0], layers[1:]
        super().__init__(name_scope)
        if layers and isinstance(layers[0], (tuple, list)):
            for name, layer in layers:
                self.add_sublayer(str(name), layer)
        else:
            for idx, layer in enumerate(layers):
                self.add_sublayer(str(idx), layer)

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input

    def __getitem__(self, name):
        return self._sub_layers[str(name)]

    def __setitem__(self, name, layer):
        assert isinstance(layer, Layer)
        self._sub_layers[str(name)] = layer

    def __delitem__(self, name):
        del self._sub_layers[str(name)]

    def __len__(self):
        return len(self._sub_layers)
