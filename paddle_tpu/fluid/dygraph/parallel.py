"""DyGraph data parallelism — reference ``dygraph/parallel.py``
(``prepare_context``, ``ParallelEnv``, ``DataParallel`` with
``scale_loss`` / ``apply_collective_grads``).

TPU-native: ranks are jax PROCESSES (one per host, bootstrapped by
``paddle_tpu.distributed.launch`` / ``jax.distributed.initialize`` —
distributed/env.py). ``apply_collective_grads`` sum-reduces each
parameter's gradient across processes with a jit-compiled reduction over
the global device set (the eager-mode analogue of the reference's NCCL
allreduce); with one process it is a no-op, so the same training loop
runs anywhere.
"""

import os

import numpy as np

from .base import VarBase

__all__ = ["prepare_context", "ParallelEnv", "Env", "DataParallel"]


class ParallelEnv:
    """Rank/world info (reference ``dygraph/parallel.py`` Env): reads the
    launcher's env vars, falling back to the jax runtime."""

    def __init__(self):
        # env vars first: touching jax here would initialize the backend
        # BEFORE jax.distributed.initialize can run (prepare_context)
        nranks = os.environ.get("PADDLE_TRAINERS_NUM")
        rank = os.environ.get("PADDLE_TRAINER_ID")
        if nranks is None or rank is None:
            import jax

            nranks = jax.process_count() if nranks is None else nranks
            rank = jax.process_index() if rank is None else rank
        self._nranks = int(nranks)
        self._local_rank = int(rank)

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return 0  # one chip per process under the TPU runtime

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


Env = ParallelEnv


def prepare_context(strategy=None):
    """Initialize the multi-process context when launched distributed
    (reference prepare_context creates the NCCL communicator; here the
    rendezvous is jax.distributed, done by distributed/env.py)."""
    from ... import distributed as dist

    dist.env.init_parallel_env()
    return ParallelEnv()


class DataParallel:
    """Wraps a dygraph Layer for multi-process data parallelism."""

    def __init__(self, layers, strategy=None):
        self._layers = layers
        self._env = strategy if isinstance(strategy, ParallelEnv) \
            else ParallelEnv()
        self._psum = None

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def scale_loss(self, loss):
        """Divide by nranks so the summed cross-process gradient is the
        global-batch mean (reference DataParallel.scale_loss)."""
        if self._env.nranks <= 1:
            return loss
        return loss * (1.0 / self._env.nranks)

    def _sum_across_processes(self, arr):
        """Sum a per-process array over all processes ON DEVICE: stack
        the local shards into a global [P, ...] array and jit a sum with
        replicated output sharding — XLA emits the all-reduce over
        ICI/DCN. Host allgather is only the last-ditch fallback."""
        import jax

        if self._psum is None:
            try:
                from jax.sharding import (Mesh, NamedSharding,
                                          PartitionSpec)

                nproc = self._env.nranks
                devs = np.asarray([jax.local_devices(process_index=p)[0]
                                   for p in range(nproc)])
                mesh = Mesh(devs, ("p",))
                shard = NamedSharding(mesh, PartitionSpec("p"))
                rep = NamedSharding(mesh, PartitionSpec())

                def device_sum(x):
                    g = jax.make_array_from_single_device_arrays(
                        (nproc,) + x.shape, shard,
                        [jax.device_put(np.asarray(x)[None],
                                        devs[self._env.local_rank])])
                    out = jax.jit(lambda a: a.sum(0),
                                  out_shardings=rep)(g)
                    return out.addressable_shards[0].data

                self._psum = device_sum
            except Exception:  # e.g. no global runtime — host fallback
                from jax.experimental import multihost_utils

                def host_sum(x):
                    g = multihost_utils.process_allgather(x)
                    return np.asarray(g).sum(axis=0)

                self._psum = host_sum
        return self._psum(arr)

    def apply_collective_grads(self):
        """Sum every parameter gradient across processes (the loss was
        divided by nranks in ``scale_loss``, so the summed gradient is
        the global-batch mean) — reference
        DataParallel.apply_collective_grads. Call between
        ``loss.backward()`` and ``optimizer.minimize``."""
        if self._env.nranks <= 1:
            return
        for p in self._layers.parameters():
            if getattr(p, "_grad", None) is None:
                continue
            p._grad = self._sum_across_processes(p._grad)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_dict(self, *a, **kw):
        return self._layers.set_dict(*a, **kw)
