"""Dygraph-mode profiler hooks.

Parity: reference ``dygraph/profiler.py`` (``start_gperf_profiler:25`` /
``stop_gperf_profiler:29``), which gperf-profiles the imperative C++
engine. Here the eager engine IS the XLA runtime, so the equivalent
signal is a jax.profiler trace of the eager op dispatches: the trace
lands in ``PADDLE_TPU_GPERF_DIR`` (default ``./dygraph_profile``) and is
viewable in TensorBoard / Perfetto, alongside the host-span profiler in
``fluid/profiler.py``.
"""

import os

__all__ = ["start_gperf_profiler", "stop_gperf_profiler"]

_active = [False]


def start_gperf_profiler():
    import jax

    if _active[0]:  # symmetric with stop(): re-entry is a no-op
        return
    logdir = os.environ.get("PADDLE_TPU_GPERF_DIR", "./dygraph_profile")
    jax.profiler.start_trace(logdir)
    _active[0] = True


def stop_gperf_profiler():
    import jax

    if _active[0]:
        jax.profiler.stop_trace()
        _active[0] = False
