"""Dygraph-mode profiler hooks.

Parity: reference ``dygraph/profiler.py`` (``start_gperf_profiler:25`` /
``stop_gperf_profiler:29``), which gperf-profiles the imperative C++
engine. Here the eager engine IS the XLA runtime, so start/stop route
through the SHARED ``fluid/profiler.py`` machinery: host RecordEvent
spans are collected (visible in ``profiler.summary()`` and as monitor
histograms) and a jax.profiler device trace lands in
``PADDLE_TPU_GPERF_DIR`` (default ``./dygraph_profile``), viewable in
TensorBoard / Perfetto. The stop side is silent — gperf never printed a
table — but the collected spans stay queryable until the next
``reset_profiler()``.
"""

import os

from .. import monitor as _monitor
from .. import profiler as _profiler

__all__ = ["start_gperf_profiler", "stop_gperf_profiler"]

_active = [False]

_M_SESSIONS = _monitor.counter(
    "dygraph_profiler_sessions_total",
    help="start_gperf_profiler/stop_gperf_profiler cycles")


def start_gperf_profiler():
    if _active[0]:  # symmetric with stop(): re-entry is a no-op
        return
    logdir = os.environ.get("PADDLE_TPU_GPERF_DIR", "./dygraph_profile")
    _profiler.start_profiler(state="All", trace_dir=logdir)
    _active[0] = True


def stop_gperf_profiler():
    if _active[0]:
        _profiler.stop_profiler(silent=True)
        _active[0] = False
        _M_SESSIONS.inc()
