"""DyGraph core: eager variables, tape tracer, backward engine.

Parity: reference ``paddle/fluid/imperative/`` — ``Tracer::TraceOp``
(tracer.h:44), ``VarBase``/``OpBase`` (layer.h:55,351), ``BasicEngine``
(engine.cc:181) — redesigned TPU-first:

* Eager ops execute through the SAME lowering rules as the static executor
  (one kernel story, the ``PreparedOp`` analogue), on concrete ``jax.Array``s
  with async dispatch.
* The tape records (op, inputs, outputs, attrs, rng keys). ``backward()`` is
  reverse accumulation where each op's VJP comes from ``jax.vjp`` over its
  lowering rule — no per-op grad kernels.
* Each eager op call is jit-compiled and cached keyed on
  (op type, input avals, attrs) so steady-state dispatch is cheap
  (the reference's dygraph per-op kernel cache analogue).
"""

import contextlib

import numpy as np

from .. import framework
from .. import rng as _rng
from ..registry import registry

__all__ = ["guard", "to_variable", "enabled", "VarBase", "Tracer",
           "no_grad", "grad_enabled"]


class _EagerOp:
    """Duck-types framework.Operator for lowering rules."""

    __slots__ = ("type", "inputs", "outputs", "attrs")

    def __init__(self, type, inputs, outputs, attrs):
        self.type = type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = dict(attrs or {})

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)


class _EagerCtx:
    """Duck-types LowerCtx over concrete arrays."""

    def __init__(self, env, keys=None):
        self.env = env
        self._keys = list(keys) if keys else []
        self.used_keys = []

    def get(self, name):
        return self.env[name]

    def get_input(self, op, slot, default=None):
        names = op.input(slot)
        return self.env[names[0]] if names else default

    def get_inputs(self, op, slot):
        return [self.env[n] for n in op.input(slot)]

    def set(self, name, value):
        self.env[name] = value

    def set_output(self, op, slot, value):
        names = op.output(slot)
        if names:
            self.env[names[0]] = value

    def var(self, name):
        return None

    def var_dtype(self, name):
        # eager mode has no declared program vars; lowerings asking for
        # an output's declared dtype get f32 (matching LowerCtx's
        # missing-var default)
        return np.dtype("float32")

    def next_rng(self):
        key = self._keys.pop(0)
        self.used_keys.append(key)
        return key


class VarBase:
    """Eager tensor with autograd metadata (reference imperative::VarBase)."""

    _counter = [0]

    def __init__(self, value, name=None, stop_gradient=False, persistable=False):
        import jax.numpy as jnp

        self._ivar = value if hasattr(value, "dtype") else jnp.asarray(value)
        VarBase._counter[0] += 1
        self.name = name or ("eager_var_%d" % VarBase._counter[0])
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad = None

    # -- value access -------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._ivar.shape)

    @property
    def dtype(self):
        return np.dtype(self._ivar.dtype)

    def numpy(self):
        return np.asarray(self._ivar)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def detach(self):
        return VarBase(self._ivar, stop_gradient=True)

    def set_value(self, value):
        import jax.numpy as jnp

        self._ivar = jnp.asarray(value, dtype=self._ivar.dtype)

    # -- autograd -----------------------------------------------------------
    def backward(self, backward_strategy=None):
        # backward_strategy (reference BackwardStrategy) is accepted for
        # parity; tape replay is always deterministic (see
        # backward_strategy.py), so sort_sum_gradient changes nothing
        tracer = framework._dygraph_tracer()
        if tracer is None:
            raise RuntimeError("backward() outside dygraph guard")
        tracer.run_backward(self)

    # -- op sugar -----------------------------------------------------------
    def _binary(self, other, op_type, reverse=False):
        tracer = framework._dygraph_tracer()
        if not isinstance(other, VarBase):
            other = VarBase(np.asarray(other, self.dtype), stop_gradient=True)
        a, b = (other, self) if reverse else (self, other)
        (out,) = tracer.trace_op(op_type, {"X": [a], "Y": [b]}, ["Out"], {"axis": -1})
        return out

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    def __radd__(self, o):
        return self._binary(o, "elementwise_add", True)

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    def __rmul__(self, o):
        return self._binary(o, "elementwise_mul", True)

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __neg__(self):
        tracer = framework._dygraph_tracer()
        (out,) = tracer.trace_op("scale", {"X": [self]}, ["Out"], {"scale": -1.0})
        return out

    def __repr__(self):
        return "VarBase(name=%s, shape=%s,\n%r)" % (self.name, self.shape,
                                                    self.numpy())

    def astype(self, dtype):
        tracer = framework._dygraph_tracer()
        (out,) = tracer.trace_op(
            "cast", {"X": [self]}, ["Out"],
            {"out_dtype": framework.dtype_str(framework.convert_dtype(dtype))})
        return out


class _TapeEntry:
    __slots__ = ("op_type", "attrs", "in_slots", "out_slots", "keys")

    def __init__(self, op_type, attrs, in_slots, out_slots, keys):
        self.op_type = op_type
        self.attrs = attrs
        self.in_slots = in_slots  # {slot: [VarBase]}
        self.out_slots = out_slots
        self.keys = keys


def _attr_key(attrs):
    def freeze(v):
        if isinstance(v, (list, tuple)):
            return tuple(freeze(x) for x in v)
        return v

    return tuple(sorted((k, freeze(v)) for k, v in attrs.items()))


class Tracer:
    """Eager dispatcher + tape (reference imperative::Tracer + BasicEngine)."""

    def __init__(self):
        self._tape = []
        self._rng = _rng.root_key(0)
        self._no_grad = False
        self._fn_cache = {}
        self._program_recorder = None  # set by jit tracing

    def seed(self, s):
        self._rng = _rng.root_key(s)

    # ------------------------------------------------------------------
    def trace_op(self, op_type, input_slots, out_slot_names, attrs=None):
        """input_slots: {slot: [VarBase]}; returns list of output VarBases
        aligned with out_slot_names (one var per slot)."""
        import jax

        attrs = dict(attrs or {})
        from ..registry import EXECUTED_OP_TYPES

        EXECUTED_OP_TYPES.add(op_type)
        info = registry.get(op_type)
        n_keys = 2 if info.has_state else 0
        keys = []
        if n_keys:
            self._rng, k = jax.random.split(self._rng)
            keys = list(jax.random.split(k, n_keys))

        in_names = {s: [("%s#%d" % (s, i)) for i in range(len(vs))]
                    for s, vs in input_slots.items()}
        out_names = {s: [s + "@out"] for s in out_slot_names}
        eop = _EagerOp(op_type, in_names, out_names, attrs)

        flat_in = [v._ivar for vs in input_slots.values() for v in vs]
        structure = [(s, len(vs)) for s, vs in input_slots.items()]

        cache_key = (
            op_type,
            _attr_key(attrs),
            tuple((s, n) for s, n in structure),
            tuple((tuple(a.shape), str(a.dtype)) for a in flat_in),
            tuple(out_slot_names),
        )
        fn = self._fn_cache.get(cache_key)
        if fn is None:
            def raw(flat_vals, keys):
                env = {}
                i = 0
                for s, n in structure:
                    for j in range(n):
                        env[in_names[s][j]] = flat_vals[i]
                        i += 1
                ctx = _EagerCtx(env, keys)
                info.lower(ctx, eop)
                return [env.get(s + "@out") for s in out_slot_names]

            fn = jax.jit(raw)
            self._fn_cache[cache_key] = fn

        outs = fn(flat_in, keys)
        out_vars = [VarBase(o) if o is not None else None for o in outs]

        if not self._no_grad:
            # record for backward unless every input is stop_gradient
            if any(not v.stop_gradient for vs in input_slots.values() for v in vs):
                self._tape.append(
                    _TapeEntry(op_type, attrs, dict(input_slots),
                               dict(zip(out_slot_names, out_vars)), keys))
            else:
                for v in out_vars:
                    if v is not None:
                        v.stop_gradient = True

        if self._program_recorder is not None:
            self._program_recorder.record(op_type, input_slots, out_slot_names,
                                          out_vars, attrs)
        return out_vars

    # ------------------------------------------------------------------
    def run_backward(self, loss):
        import jax
        import jax.numpy as jnp

        grads = {id(loss): jnp.ones_like(loss._ivar)}
        var_of = {id(loss): loss}
        for entry in reversed(self._tape):
            out_vars = [v for v in entry.out_slots.values() if v is not None]
            if not any(id(v) in grads for v in out_vars):
                continue
            in_vars = [v for vs in entry.in_slots.values() for v in vs]
            info = registry.get(entry.op_type)
            structure = [(s, len(vs)) for s, vs in entry.in_slots.items()]
            in_names = {s: [("%s#%d" % (s, i)) for i in range(n)]
                        for s, n in structure}
            out_slot_names = list(entry.out_slots.keys())
            out_names = {s: [s + "@out"] for s in out_slot_names}
            eop = _EagerOp(entry.op_type, in_names, out_names, entry.attrs)

            def f(flat_vals):
                env = {}
                i = 0
                for s, n in structure:
                    for j in range(n):
                        env[in_names[s][j]] = flat_vals[i]
                        i += 1
                ctx = _EagerCtx(env, entry.keys)
                info.lower(ctx, eop)
                return [env.get(s + "@out") for s in out_slot_names]

            primals = [v._ivar for v in in_vars]
            outs, vjp_fn = jax.vjp(f, primals)
            cot = []
            for s, ov in entry.out_slots.items():
                if ov is not None and id(ov) in grads:
                    cot.append(grads[id(ov)])
                else:
                    idx = out_slot_names.index(s)
                    cot.append(jnp.zeros_like(outs[idx]) if outs[idx] is not None else None)
            (in_grads,) = vjp_fn(cot)
            for v, g in zip(in_vars, in_grads):
                if v.stop_gradient or g is None:
                    continue
                if id(v) in grads:
                    grads[id(v)] = grads[id(v)] + g
                else:
                    grads[id(v)] = g
                    var_of[id(v)] = v
        # write leaf grads (persistable = parameters, or user leaves)
        for vid, g in grads.items():
            v = var_of[vid]
            if v._grad is not None:
                v._grad = v._grad + g
            else:
                v._grad = g
        self._tape.clear()

    @contextlib.contextmanager
    def _no_grad_guard(self):
        old = self._no_grad
        self._no_grad = True
        try:
            yield
        finally:
            self._no_grad = old


def enabled():
    return framework.in_dygraph_mode()


@contextlib.contextmanager
def guard(place=None):
    tracer = Tracer()
    with framework._dygraph_guard(tracer):
        yield


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name, stop_gradient=True)


@contextlib.contextmanager
def no_grad():
    tracer = framework._dygraph_tracer()
    if tracer is None:
        yield
    else:
        with tracer._no_grad_guard():
            yield


grad_enabled = no_grad
