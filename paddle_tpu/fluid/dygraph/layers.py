"""Layer base class for dygraph modules (reference ``dygraph/layers.py``)."""

import numpy as np

from .. import framework
from ..initializer import Constant, Xavier
from ..param_attr import ParamAttr
from .base import VarBase, to_variable

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = name_scope or self.__class__.__name__.lower()
        self._dtype = dtype
        self._parameters = {}
        self._sub_layers = {}
        self.training = True

    def full_name(self):
        return self._full_name

    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    # -- parameter management ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype="float32", is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = attr.initializer or default_initializer or (
            Constant(0.0) if is_bias else Xavier()
        )
        value = _run_initializer(init, shape, dtype)
        # parameters get STABLE generated names (reference
        # layer_object_helper naming): "<layer>.w_k" from the layer's
        # unique-name scope rather than the raw eager counter, so
        # name-keyed state (optimizer accumulators) survives a
        # rebuild-and-restore under the same unique_name scope
        from .. import unique_name

        name = attr.name or unique_name.generate(
            "%s.%s" % (self._full_name, "b" if is_bias else "w"))
        p = VarBase(value, name=name, stop_gradient=not attr.trainable,
                    persistable=True)
        if attr.shard is not None:
            if len(attr.shard) != len(shape):
                raise ValueError(
                    "ParamAttr.shard %r must have one entry per param "
                    "dim %r" % (attr.shard, tuple(shape)))
            p.shard_spec = tuple(attr.shard)
        p.trainable = attr.trainable
        p.regularizer = attr.regularizer
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return [p for p in out if p is not None]

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def named_parameters(self, prefix=""):
        for name, p in self._parameters.items():
            if p is not None:
                yield (prefix + name if not prefix else prefix + "." + name), p
        for lname, l in self._sub_layers.items():
            sub_prefix = lname if not prefix else prefix + "." + lname
            yield from l.named_parameters(sub_prefix)

    # -- state dict ---------------------------------------------------------
    def state_dict(self, include_sublayers=True):
        return {name: p for name, p in self.named_parameters()}

    def set_dict(self, state_dict, include_sublayers=True):
        for name, p in self.named_parameters():
            if name in state_dict:
                val = state_dict[name]
                p.set_value(val.numpy() if isinstance(val, VarBase) else val)

    load_dict = set_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and getattr(value, "persistable", False):
            self.__dict__.setdefault("_parameters", {})[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", {})[name] = value
        object.__setattr__(self, name, value)


def _run_initializer(init, shape, dtype):
    """Runs a static-graph initializer eagerly via a one-op program."""
    import paddle_tpu.fluid as fluid

    prog = framework.Program()
    startup = framework.Program()
    with framework.program_guard(prog, startup):
        blk = prog.global_block()
        v = blk.create_var(name="out", shape=list(shape), dtype=dtype)
        init(v, blk)
    exe = fluid.Executor()
    from ..executor import Scope, scope_guard

    with scope_guard(Scope()):
        (val,) = exe.run(prog, fetch_list=["out"], return_numpy=False)
    return val
