"""Dygraph learning-rate decay objects (reference
``python/paddle/fluid/dygraph/learning_rate_scheduler.py:27-553``):
step-counting schedulers an optimizer accepts as ``learning_rate=`` in
dygraph mode. Each ``__call__`` returns the current LR and advances the
counter — the eager minimize path invokes it once per step.

TPU-native deviation: the reference materializes each LR as a [1]
framework Variable per step; here the schedule is pure host-scalar math
(a Python float). The LR enters the eagerly-dispatched update ops as a
scalar operand, so a changing LR never retriggers compilation and never
costs a device round trip.
"""

import math

__all__ = [
    "LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
    "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
    "CosineDecay", "NoamDecay",
]


class LearningRateDecay:
    """Base: counts optimizer steps; subclasses define ``step()`` → LR
    for the CURRENT ``step_num`` (reference ``:27``). ``begin`` seeds
    the counter and ``step`` is its per-call increment."""

    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = int(begin)
        self.step_size = int(step)
        self.dtype = dtype

    def __call__(self):
        lr = float(self.step())
        self.step_num += self.step_size
        return lr

    def step(self):
        raise NotImplementedError()

    def __float__(self):
        # a static-graph optimizer folds its LR with float(...); decay
        # OBJECTS are dygraph-only (the static twins live in
        # layers.learning_rate_scheduler) — fail loudly, not silently
        # freezing the first LR into the program
        raise TypeError(
            "%s is a dygraph-mode scheduler; in static graph mode use "
            "fluid.layers.%s instead" % (
                type(self).__name__,
                getattr(self, "_static_twin", "learning_rate_scheduler")))

    # convenience for checkpointing (the reference exposes bare
    # attributes; dict form round-trips through save/load_dygraph)
    def state_dict(self):
        return {"step_num": self.step_num}

    def set_state_dict(self, state):
        self.step_num = int(state["step_num"])


class PiecewiseDecay(LearningRateDecay):
    """``values[i]`` while ``step_num < boundaries[i]``, last value
    afterwards (reference ``:70``)."""

    _static_twin = "piecewise_decay"

    def __init__(self, boundaries, values, begin, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        if len(values) != len(boundaries) + 1:
            raise ValueError(
                "need len(values) == len(boundaries) + 1, got %d and %d"
                % (len(values), len(boundaries)))
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return v
        return self.values[-1]


class _RatioDecay(LearningRateDecay):
    """Shared shape of the four ratio schedulers: ``div = step_num /
    decay_steps`` (floored when ``staircase``)."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = float(learning_rate)
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def _div(self):
        d = self.step_num / self.decay_steps
        return float(math.floor(d)) if self.staircase else d


class NaturalExpDecay(_RatioDecay):
    """lr * e^(-decay_rate * div) — reference ``:127``."""

    _static_twin = "natural_exp_decay"

    def step(self):
        return self.learning_rate * math.exp(-self.decay_rate * self._div())


class ExponentialDecay(_RatioDecay):
    """lr * decay_rate^div — reference ``:206``."""

    _static_twin = "exponential_decay"

    def step(self):
        return self.learning_rate * (self.decay_rate ** self._div())


class InverseTimeDecay(_RatioDecay):
    """lr / (1 + decay_rate * div) — reference ``:286``."""

    _static_twin = "inverse_time_decay"

    def step(self):
        return self.learning_rate / (1.0 + self.decay_rate * self._div())


class PolynomialDecay(LearningRateDecay):
    """(lr - end) * (1 - step/decay_steps)^power + end, optionally
    cycling by inflating decay_steps to the enclosing multiple
    (reference ``:360``)."""

    _static_twin = "polynomial_decay"

    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = float(learning_rate)
        self.decay_steps = decay_steps
        self.end_learning_rate = float(end_learning_rate)
        self.power = power
        self.cycle = cycle

    def step(self):
        n, steps = self.step_num, self.decay_steps
        if self.cycle:
            div = math.ceil(n / float(steps))
            if n == 0:
                div = 1.0
            steps = steps * div
        else:
            n = min(n, steps)
        return ((self.learning_rate - self.end_learning_rate)
                * ((1.0 - n / steps) ** self.power)
                + self.end_learning_rate)


class CosineDecay(LearningRateDecay):
    """lr * 0.5 * (cos(epoch * pi / epochs) + 1) with epoch =
    floor(step / step_each_epoch) — reference ``:450``."""

    _static_twin = "cosine_decay"

    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = float(learning_rate)
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        epoch = math.floor(self.step_num / self.step_each_epoch)
        return (self.learning_rate * 0.5
                * (math.cos(epoch * math.pi / self.epochs) + 1.0))


class NoamDecay(LearningRateDecay):
    """d_model^-0.5 * min(step^-0.5, warmup^-1.5 * step) — reference
    ``:506``. ``begin`` defaults to 1 (step 0 would divide by zero)."""

    _static_twin = "noam_decay"

    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32"):
        super().__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        a = self.step_num ** -0.5
        b = (self.warmup_steps ** -1.5) * self.step_num
        return (self.d_model ** -0.5) * min(a, b)
