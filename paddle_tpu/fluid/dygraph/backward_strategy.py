"""BackwardStrategy (reference ``dygraph/backward_strategy.py`` — a
bound C++ struct with one knob)."""

__all__ = ["BackwardStrategy"]


class BackwardStrategy:
    """``sort_sum_gradient``: the reference sums a var's gradient
    contributions in a deterministic (sorted) order when True. The TPU
    tape replays in recorded order and accumulates with jnp adds inside
    one compiled step, so gradient accumulation here is ALWAYS
    deterministic — the knob is accepted for API parity and recorded,
    but both settings produce the same (deterministic) result."""

    def __init__(self):
        self.sort_sum_gradient = False
