"""DyGraph (eager) mode — reference ``python/paddle/fluid/dygraph/``."""

from . import (backward_strategy, base, checkpoint, container, jit, layers,
               learning_rate_scheduler, nn, parallel, profiler)
from .backward_strategy import BackwardStrategy  # noqa: F401
from .container import Sequential  # noqa: F401
from .parallel import DataParallel, ParallelEnv, prepare_context  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    CosineDecay,
    ExponentialDecay,
    InverseTimeDecay,
    LearningRateDecay,
    NaturalExpDecay,
    NoamDecay,
    PiecewiseDecay,
    PolynomialDecay,
)
from .base import (  # noqa: F401
    Tracer,
    VarBase,
    enabled,
    guard,
    no_grad,
    to_variable,
)
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from .jit import TracedLayer  # noqa: F401
from .layers import Layer  # noqa: F401
from .nn import *  # noqa: F401,F403
