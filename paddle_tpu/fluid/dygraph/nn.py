"""DyGraph NN modules (reference ``dygraph/nn.py`` — 16 modules,
SURVEY Appendix A)."""

import numpy as np

from .. import framework
from ..initializer import Constant, Normal, Xavier
from .base import VarBase, to_variable
from .layers import Layer

__all__ = [
    "Conv2D", "Conv3D", "Pool2D", "FC", "Linear", "BatchNorm", "Embedding",
    "LayerNorm", "PRelu", "BilinearTensorProduct", "Conv2DTranspose",
    "Conv3DTranspose",
    "GroupNorm", "SpectralNorm", "GRUUnit", "NCE", "TreeConv", "Dropout",
]


def _tracer():
    t = framework._dygraph_tracer()
    if t is None:
        raise RuntimeError("dygraph modules require fluid.dygraph.guard()")
    return t


class Conv2D(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, stride=1, padding=0, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = [stride] * 2 if isinstance(stride, int) else list(stride)
        self._padding = [padding] * 2 if isinstance(padding, int) else list(padding)
        self._dilation = [dilation] * 2 if isinstance(dilation, int) else list(dilation)
        self._act = act
        if isinstance(filter_size, int):
            filter_size = [filter_size] * 2
        fan = num_channels * filter_size[0] * filter_size[1] // self._groups
        self.weight = self.create_parameter(
            [num_filters, num_channels // self._groups] + list(filter_size),
            param_attr, dtype, default_initializer=Normal(0.0, (2.0 / fan) ** 0.5))
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, input):
        t = _tracer()
        (out,) = t.trace_op(
            "conv2d", {"Input": [input], "Filter": [self.weight]}, ["Output"],
            {"strides": self._stride, "paddings": self._padding,
             "dilations": self._dilation, "groups": self._groups})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": 1})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class Conv3D(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, stride=1, padding=0, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = [stride] * 3 if isinstance(stride, int) else list(stride)
        self._padding = [padding] * 3 if isinstance(padding, int) else list(padding)
        self._act = act
        if isinstance(filter_size, int):
            filter_size = [filter_size] * 3
        self.weight = self.create_parameter(
            [num_filters, num_channels // self._groups] + list(filter_size),
            param_attr, dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, input):
        t = _tracer()
        (out,) = t.trace_op(
            "conv3d", {"Input": [input], "Filter": [self.weight]}, ["Output"],
            {"strides": self._stride, "paddings": self._padding,
             "groups": self._groups})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": 1})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class Conv2DTranspose(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, padding=0, stride=1, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._stride = [stride] * 2 if isinstance(stride, int) else list(stride)
        self._padding = [padding] * 2 if isinstance(padding, int) else list(padding)
        self._dilation = ([dilation] * 2 if isinstance(dilation, int)
                          else list(dilation))
        self._groups = groups or 1
        self._act = act
        if isinstance(filter_size, int):
            filter_size = [filter_size] * 2
        self.weight = self.create_parameter(
            [num_channels, num_filters // self._groups] + list(filter_size),
            param_attr, dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, input):
        t = _tracer()
        (out,) = t.trace_op(
            "conv2d_transpose", {"Input": [input], "Filter": [self.weight]},
            ["Output"], {"strides": self._stride, "paddings": self._padding,
                         "dilations": self._dilation, "groups": self._groups})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": 1})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class Conv3DTranspose(Layer):
    """Eager 3D transposed conv (reference ``dygraph/nn.py`` Conv3DTranspose)."""

    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, padding=0, stride=1, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._stride = [stride] * 3 if isinstance(stride, int) else list(stride)
        self._padding = [padding] * 3 if isinstance(padding, int) else list(padding)
        self._dilation = ([dilation] * 3 if isinstance(dilation, int)
                          else list(dilation))
        self._groups = groups or 1
        self._act = act
        if isinstance(filter_size, int):
            filter_size = [filter_size] * 3
        self.weight = self.create_parameter(
            [num_channels, num_filters // self._groups] + list(filter_size),
            param_attr, dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, input):
        t = _tracer()
        (out,) = t.trace_op(
            "conv3d_transpose", {"Input": [input], "Filter": [self.weight]},
            ["Output"], {"strides": self._stride, "paddings": self._padding,
                         "dilations": self._dilation, "groups": self._groups})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": 1})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=-1, pool_type="max",
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 ceil_mode=False, exclusive=True, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        (out,) = _tracer().trace_op("pool2d", {"X": [input]}, ["Out"], self._attrs)
        return out


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(None, dtype)
        self._act = act
        self.weight = self.create_parameter([input_dim, output_dim], param_attr,
                                            dtype)
        self.bias = self.create_parameter([output_dim], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, input):
        t = _tracer()
        (out,) = t.trace_op("matmul", {"X": [input], "Y": [self.weight]},
                            ["Out"], {"transpose_X": False, "transpose_Y": False,
                                      "alpha": 1.0})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": -1})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class FC(Layer):
    """Reference dygraph FC: flattens input to 2-D then matmul."""

    def __init__(self, name_scope=None, size=None, num_flatten_dims=1,
                 param_attr=None, bias_attr=None, act=None, dtype="float32",
                 input_dim=None):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, in_features):
        self.weight = self.create_parameter([in_features, self._size],
                                            self._param_attr, self._dtype)
        self.bias = self.create_parameter([self._size], self._bias_attr,
                                          self._dtype, is_bias=True)

    def forward(self, input):
        if self.weight is None:
            in_features = int(np.prod(input.shape[self._num_flatten_dims:]))
            self._build(in_features)
        t = _tracer()
        (out,) = t.trace_op(
            "mul", {"X": [input], "Y": [self.weight]}, ["Out"],
            {"x_num_col_dims": self._num_flatten_dims, "y_num_col_dims": 1})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": self._num_flatten_dims})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None,
                 is_test=False, momentum=0.9, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32", data_layout="NCHW",
                 use_global_stats=False):
        super().__init__(name_scope, dtype)
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter([num_channels], param_attr, dtype,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], bias_attr, dtype,
                                          is_bias=True)
        self._mean = VarBase(np.zeros(num_channels, dtype), stop_gradient=True,
                             persistable=True)
        self._variance = VarBase(np.ones(num_channels, dtype),
                                 stop_gradient=True, persistable=True)

    def forward(self, input):
        t = _tracer()
        outs = t.trace_op(
            "batch_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
            {"momentum": self._momentum, "epsilon": self._epsilon,
             "is_test": not self.training, "data_layout": self._data_layout,
             "use_global_stats": self._use_global_stats})
        y = outs[0]
        if outs[1] is not None:  # training: commit running stats
            self._mean._ivar = outs[1]._ivar
            self._variance._ivar = outs[2]._ivar
        if self._act:
            (y,) = t.trace_op(self._act, {"X": [y]}, ["Out"], {})
        return y


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 is_distributed=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx
        self.weight = self.create_parameter(size, param_attr, dtype,
                                            default_initializer=Xavier())

    def forward(self, input):
        (out,) = _tracer().trace_op(
            "lookup_table", {"W": [self.weight], "Ids": [input]}, ["Out"],
            {"padding_idx": self._padding_idx})
        return out


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None, scale=True,
                 shift=True, begin_norm_axis=1, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._epsilon = epsilon
        self._begin_norm_axis = begin_norm_axis
        self._act = act
        n = int(np.prod(normalized_shape)) if normalized_shape else None
        self.weight = self.create_parameter([n], param_attr, dtype,
                                            default_initializer=Constant(1.0)) if scale else None
        self.bias = self.create_parameter([n], bias_attr, dtype,
                                          is_bias=True) if shift else None

    def forward(self, input):
        t = _tracer()
        slots = {"X": [input]}
        if self.weight is not None:
            slots["Scale"] = [self.weight]
        if self.bias is not None:
            slots["Bias"] = [self.bias]
        outs = t.trace_op("layer_norm", slots, ["Y", "Mean", "Variance"],
                          {"epsilon": self._epsilon,
                           "begin_norm_axis": self._begin_norm_axis})
        y = outs[0]
        if self._act:
            (y,) = t.trace_op(self._act, {"X": [y]}, ["Out"], {})
        return y


class GroupNorm(Layer):
    def __init__(self, name_scope=None, channels=None, groups=None,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self.weight = self.create_parameter([channels], param_attr, dtype,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([channels], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, input):
        t = _tracer()
        outs = t.trace_op(
            "group_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias]},
            ["Y", "Mean", "Variance"],
            {"groups": self._groups, "epsilon": self._epsilon})
        y = outs[0]
        if self._act:
            (y,) = t.trace_op(self._act, {"X": [y]}, ["Out"], {})
        return y


class SpectralNorm(Layer):
    def __init__(self, name_scope=None, weight_shape=None, dim=0,
                 power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self._u = VarBase(np.random.randn(h).astype(dtype), stop_gradient=True,
                          persistable=True)
        self._v = VarBase(np.random.randn(w).astype(dtype), stop_gradient=True,
                          persistable=True)

    def forward(self, weight):
        (out,) = _tracer().trace_op(
            "spectral_norm", {"Weight": [weight], "U": [self._u], "V": [self._v]},
            ["Out"], {"dim": self._dim, "power_iters": self._power_iters,
                      "eps": self._eps})
        return out


class PRelu(Layer):
    def __init__(self, name_scope=None, mode="all", channel=None,
                 input_shape=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel]
        else:
            shape = list(input_shape[1:])
        self.weight = self.create_parameter(shape, param_attr, dtype,
                                            default_initializer=Constant(0.25))

    def forward(self, input):
        (out,) = _tracer().trace_op(
            "prelu", {"X": [input], "Alpha": [self.weight]}, ["Out"],
            {"mode": self._mode})
        return out


class BilinearTensorProduct(Layer):
    def __init__(self, name_scope=None, input1_dim=None, input2_dim=None,
                 output_dim=None, act=None, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], param_attr, dtype)
        self.bias = self.create_parameter([1, output_dim], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, x, y):
        t = _tracer()
        slots = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            slots["Bias"] = [self.bias]
        (out,) = t.trace_op("bilinear_tensor_product", slots, ["Out"], {})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        outs = _tracer().trace_op(
            "dropout", {"X": [input]}, ["Out", "Mask"],
            {"dropout_prob": self._p, "is_test": not self.training,
             "dropout_implementation": self._impl})
        return outs[0]


class GRUUnit(Layer):
    """Single GRU step (reference dygraph GRUUnit)."""

    def __init__(self, name_scope=None, size=None, param_attr=None,
                 bias_attr=None, activation="tanh", gate_activation="sigmoid",
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        # size is 3*hidden in the reference API
        self._hidden = size // 3
        h = self._hidden
        self.weight = self.create_parameter([h, 3 * h], param_attr, dtype)
        self.bias = self.create_parameter([1, 3 * h], bias_attr, dtype,
                                          is_bias=True)
        self._activation = activation
        self._gate_activation = gate_activation

    def forward(self, input, hidden):
        t = _tracer()
        h = self._hidden
        # gates = input + hidden @ W[:, :2h]; candidate uses r * (hidden @ W[:, 2h:])
        (hw,) = t.trace_op("matmul", {"X": [hidden], "Y": [self.weight]},
                           ["Out"], {"transpose_X": False, "transpose_Y": False,
                                     "alpha": 1.0})
        (g,) = t.trace_op("elementwise_add", {"X": [input], "Y": [hw]}, ["Out"],
                          {"axis": -1})
        if self.bias is not None:
            (g,) = t.trace_op("elementwise_add", {"X": [g], "Y": [self.bias]},
                              ["Out"], {"axis": -1})
        import jax.numpy as jnp

        # slice via ops for tape continuity
        def sl(v, lo, hi):
            (out,) = t.trace_op("slice", {"Input": [v]}, ["Out"],
                                {"axes": [1], "starts": [lo], "ends": [hi]})
            return out

        u = sl(g, 0, h)
        r = sl(g, h, 2 * h)
        c = sl(g, 2 * h, 3 * h)
        (u,) = t.trace_op(self._gate_activation, {"X": [u]}, ["Out"], {})
        (r,) = t.trace_op(self._gate_activation, {"X": [r]}, ["Out"], {})
        (rh,) = t.trace_op("elementwise_mul", {"X": [r], "Y": [hidden]},
                           ["Out"], {"axis": -1})
        (c2,) = t.trace_op("elementwise_add", {"X": [c], "Y": [rh]}, ["Out"],
                           {"axis": -1})
        (c3,) = t.trace_op(self._activation, {"X": [c2]}, ["Out"], {})
        one_minus_u = -u + 1.0
        new_h = u * hidden + one_minus_u * c3
        return new_h, g, c3


class NCE(Layer):
    """Eager noise-contrastive estimation head (reference dygraph NCE)
    over the static ``nce`` op — uniform negative sampling from the
    tracer's threaded PRNG (``ops/structured_loss_ops.py``)."""

    def __init__(self, name_scope=None, num_total_classes=None, dim=None,
                 sample_weight=None, param_attr=None, bias_attr=None,
                 num_neg_samples=10, sampler="uniform", custom_dist=None,
                 seed=0, is_sparse=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        if sampler != "uniform" or custom_dist is not None:
            raise NotImplementedError(
                "dygraph NCE supports sampler='uniform' only")
        if is_sparse:
            raise NotImplementedError(
                "dygraph NCE is_sparse is not supported; use the static "
                "path with a distributed embedding for sparse updates")
        if seed:
            raise NotImplementedError(
                "dygraph NCE seed is not supported; negatives draw from "
                "the tracer's threaded PRNG (set the scope seed instead)")
        if sample_weight is not None:
            raise NotImplementedError("NCE sample_weight is not supported")
        self._num_total_classes = int(num_total_classes)
        self._num_neg = int(num_neg_samples)
        self.weight = self.create_parameter([num_total_classes, dim],
                                            param_attr, dtype)
        self.bias = self.create_parameter([num_total_classes], bias_attr,
                                          dtype, is_bias=True)

    def forward(self, input, label, sample_weight=None):
        if sample_weight is not None:
            raise NotImplementedError("NCE sample_weight is not supported")
        t = _tracer()
        ins = {"Input": [input], "Label": [label], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        cost, _logits, _labels = t.trace_op(
            "nce", ins, ["Cost", "SampleLogits", "SampleLabels"],
            {"num_total_classes": self._num_total_classes,
             "num_neg_samples": self._num_neg})
        return cost


class TreeConv(Layer):
    """Eager tree-based convolution (reference dygraph TreeConv) over the
    ``tree_conv`` op (``ops/misc_ops.py`` — TBCNN as masked matmuls)."""

    def __init__(self, name_scope=None, feature_size=None, output_size=None,
                 num_filters=1, max_depth=2, act="tanh", param_attr=None,
                 bias_attr=None, name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._max_depth = int(max_depth)
        self._act = act
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters], param_attr, dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)

    def forward(self, nodes_vector, edge_set):
        t = _tracer()
        (out,) = t.trace_op(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self.weight]},
            ["Out"], {"max_depth": self._max_depth})
        if self.bias is not None:
            (out,) = t.trace_op("elementwise_add",
                                {"X": [out], "Y": [self.bias]}, ["Out"],
                                {"axis": -1})
        if self._act:
            (out,) = t.trace_op(self._act, {"X": [out]}, ["Out"], {})
        return out
