"""Dygraph -> static Program tracing (reference ``dygraph/jit.py`` +
``imperative/jit/program_desc_tracer``). Records eagerly executed ops into a
Program so it can be saved/compiled (config 5: dygraph JIT path)."""

import numpy as np

from .. import framework
from ..framework import Program
from .base import VarBase

__all__ = ["trace", "TracedLayer"]


class _ProgramRecorder:
    def __init__(self):
        self.program = Program()
        self.block = self.program.global_block()
        self._known = {}  # id(VarBase) -> var name

    def _var_for(self, vb, as_param=False):
        key = id(vb)
        if key in self._known:
            return self._known[key]
        name = vb.name
        if as_param or vb.persistable:
            param = self.block.create_parameter(
                shape=list(vb.shape), dtype=vb.dtype, name=name)
            # carry the eager param's tensor-parallel layout into the
            # static Program so CompiledProgram sees it after the trace
            spec = getattr(vb, "shard_spec", None)
            if spec is not None and param is not None:
                param.shard_spec = tuple(spec)
        else:
            self.block.create_var(name=name, shape=list(vb.shape),
                                  dtype=vb.dtype, is_data=True,
                                  stop_gradient=vb.stop_gradient)
        self._known[key] = name
        return name

    def record(self, op_type, input_slots, out_slot_names, out_vars, attrs):
        ins = {}
        for slot, vs in input_slots.items():
            ins[slot] = [self._var_for(v, as_param=v.persistable) for v in vs]
        outs = {}
        for slot, ov in zip(out_slot_names, out_vars):
            if ov is None:
                continue
            name = ov.name
            self.block.create_var(name=name, shape=list(ov.shape),
                                  dtype=ov.dtype)
            self._known[id(ov)] = name
            outs[slot] = [name]
        self.block.append_op(op_type, inputs=ins, outputs=outs, attrs=attrs)


def trace(layer, inputs):
    """Runs ``layer(*inputs)`` once, recording a static Program.

    Returns (outputs, TracedLayer)."""
    tracer = framework._dygraph_tracer()
    if tracer is None:
        raise RuntimeError("trace() must run under dygraph.guard()")
    rec = _ProgramRecorder()
    inputs = [v if isinstance(v, VarBase) else VarBase(np.asarray(v),
                                                      stop_gradient=True)
              for v in inputs]
    for v in inputs:
        rec._var_for(v)
    tracer._program_recorder = rec
    try:
        outputs = layer(*inputs)
    finally:
        tracer._program_recorder = None
    out_list = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    tl = TracedLayer(rec.program, layer,
                     [rec._known[id(v)] for v in inputs],
                     [rec._known[id(v)] for v in out_list])
    return outputs, tl


class TracedLayer:
    def __init__(self, program, layer, feed_names, fetch_names):
        self.program = program
        self._layer = layer
        self._feed_names = feed_names
        self._fetch_names = fetch_names
        self._scope = None

    @staticmethod
    def trace(layer, inputs):
        """Reference ``TracedLayer.trace`` (``dygraph/jit.py:48``):
        returns (outputs, TracedLayer). Same as the module-level
        ``trace``."""
        return trace(layer, inputs)

    def set_strategy(self, build_strategy=None, exec_strategy=None):
        """Accepted for API parity (reference ``jit.py:91``); the traced
        Program executes through the whole-block XLA jit, which owns the
        scheduling these strategies tuned."""
        self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy

    def _materialize_scope(self):
        from ..executor import Scope

        if self._scope is not None:
            return
        self._scope = Scope()
        for _, p in self._layer.named_parameters():
            self._scope.set_var(p.name, p._ivar)

    def __call__(self, inputs):
        import paddle_tpu.fluid as fluid

        self._materialize_scope()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        feed = {n: (v.numpy() if isinstance(v, VarBase) else np.asarray(v))
                for n, v in zip(self._feed_names, inputs)}
        exe = fluid.Executor()
        from ..executor import scope_guard

        with scope_guard(self._scope):
            return exe.run(self.program, feed=feed, fetch_list=self._fetch_names)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        import paddle_tpu.fluid as fluid

        self._materialize_scope()
        from ..executor import scope_guard

        exe = fluid.Executor()
        with scope_guard(self._scope):
            fetch_vars = [self.program.global_block().var(n)
                          for n in self._fetch_names]
            fluid.io.save_inference_model(dirname, self._feed_names, fetch_vars,
                                          exe, self.program)
