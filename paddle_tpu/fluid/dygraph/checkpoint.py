"""Dygraph checkpointing (reference ``dygraph/checkpoint.py``):
state-dict save/load."""

import os

import numpy as np

from .base import VarBase

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        np.savez(f, **arrays)


def load_dygraph(model_path):
    path = model_path + ".pdparams"
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    data = np.load(path)
    return {k: data[k] for k in data.files}, None
