"""Dygraph checkpointing (reference ``dygraph/checkpoint.py``):
state-dict save/load."""

import os

import numpy as np

from .base import VarBase

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    from ..core import tensor_io

    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    tensor_io.save_combine(model_path + ".pdparams", arrays)


def load_dygraph(model_path):
    path = model_path + ".pdparams"
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    # PTC1 (native serde) or legacy npz — same dispatch as fluid.io
    from ..io import _load_combined

    return _load_combined(path), None
