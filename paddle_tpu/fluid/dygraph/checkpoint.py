"""Dygraph checkpointing (reference ``dygraph/checkpoint.py``):
state-dict save/load."""

import os

import numpy as np

from .base import VarBase

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    """Parameter dicts save as ``.pdparams``; anything else (an
    optimizer ``state_dict``, whose values are plain arrays) as
    ``.pdopt`` — the reference's suffix rule (``checkpoint.py:66``)."""
    from ..core import tensor_io

    if not state_dict:
        # the reference asserts the same — an empty dict would pick the
        # .pdparams suffix and clobber a model checkpoint at this prefix
        raise ValueError("state_dict is empty, nothing to save (an "
                         "SGD-with-float-LR optimizer has no state)")
    suffix = ".pdparams"
    for v in state_dict.values():
        if not isinstance(v, VarBase):
            suffix = ".pdopt"
        break          # first value decides, like the reference
    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    tensor_io.save_combine(model_path + suffix, arrays)


def load_dygraph(model_path):
    """Returns ``(param_dict, opt_dict)``; either may be None when its
    file is absent (the reference requires .pdparams — relaxed here so
    an optimizer-only prefix loads too)."""
    from ..io import _load_combined

    para, opti = None, None
    ppath = model_path + ".pdparams"
    opath = model_path + ".pdopt"
    if os.path.exists(ppath):
        # PTC1 (native serde) or legacy npz — same dispatch as fluid.io
        para = _load_combined(ppath)
    if os.path.exists(opath):
        opti = _load_combined(opath)
    if para is None and opti is None:
        raise FileNotFoundError(ppath)
    return para, opti
