"""Root PRNG key policy.

Dropout/random-op keys derive from one root key per scope. The impl
matters enormously on TPU: threefry (jax's default) computes its hash on
the VPU and costs ~25% of a BERT-base training step in dropout masks;
the hardware ``rbg`` generator is ~free (measured on v5e: 135.7 ->
100.8 ms/step). ``unsafe_rbg`` additionally makes the per-op key
*derivation* (split/fold_in, ~25 per BERT step) trivial instead of
threefry-strength — measured 94.8 -> 87.5 ms/step — and is the TPU
default: dropout-mask randomness needs statistical quality from the
generator, not cryptographic key separation (the reference's per-op
curand Philox seeding makes the same trade). CPU and tests keep
threefry (bit-reproducibility with stock jax); override with
PADDLE_TPU_PRNG=threefry|rbg|unsafe_rbg.

The impl rides WITH the key (``jax.random.key(seed, impl=...)``), so no
global config flips and mixed-impl processes stay coherent.
"""

import os

__all__ = ["root_key", "key_data", "wrap_key_data"]


_ALIASES = {"threefry": "threefry2x32", "threefry2x32": "threefry2x32",
            "rbg": "rbg", "unsafe_rbg": "unsafe_rbg"}
_IMPL = None  # resolved once: raw key data must wrap under ONE impl


def _impl():
    global _IMPL
    if _IMPL is not None:
        return _IMPL
    env = os.environ.get("PADDLE_TPU_PRNG")
    if env:
        if env not in _ALIASES:
            raise ValueError(
                "PADDLE_TPU_PRNG=%r; expected one of %s"
                % (env, sorted(set(_ALIASES))))
        _IMPL = _ALIASES[env]
        return _IMPL
    # queries the backend — only reached from execution paths (the
    # executor/tracer), never from graph construction
    import jax

    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    _IMPL = "unsafe_rbg" if platform == "tpu" else "threefry2x32"
    return _IMPL


def root_key(seed):
    """Typed root key of the platform-appropriate impl."""
    import jax

    return jax.random.key(int(seed), impl=_impl())


def key_data(key):
    """Typed key -> raw uint32 array (jit-boundary form: raw arrays
    device_put/shard like any other state; typed KeyArrays do not)."""
    import jax

    return jax.random.key_data(key)


def wrap_key_data(raw):
    """Raw uint32 array -> typed key of the platform impl (called INSIDE
    traced step functions)."""
    import jax

    return jax.random.wrap_key_data(raw, impl=_impl())
