"""Process-wide metrics registry — reference
``paddle/fluid/platform/monitor.h`` (``StatRegistry`` + the
``STAT_ADD``/``STAT_GET`` macros) grown into the counter/gauge/histogram
triple a serving fleet actually scrapes.

The profiler (``fluid/profiler.py``) answers "where did THIS run spend
its time"; the monitor answers "what has this PROCESS done since it
started" — compile-cache hit ratios, reader throughput, watchdog
detections, predictor latency — and survives across profiler
enable/disable cycles. Everything is lock-protected, label-aware, and
``reset()``-able so tests can assert exact deltas.

Exposition:
  * ``dump_json()``           -> plain dict (bench.py embeds this)
  * ``dump_prometheus(dst)``  -> Prometheus text format 0.0.4
  * ``PADDLE_MONITOR_DUMP=/path`` dumps at interpreter exit
    (``*.json`` -> JSON, anything else -> Prometheus text).

No jax / framework imports here: the registry must be importable from
every layer (executor, reader, launcher, predictor) without cycles.
"""

import atexit
import bisect
import json
import os
import re
import threading
from collections import OrderedDict

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "get_metric", "sum_labeled", "all_metrics",
           "reset", "dump_json", "dump_prometheus", "snapshot",
           "default_buckets"]

ENV_DUMP = "PADDLE_MONITOR_DUMP"

_LOCK = threading.Lock()          # registry structure
_REGISTRY = OrderedDict()         # (name, labels_tuple) -> metric
_KINDS = {}                       # name -> (kind, help)


def default_buckets(start=1e-6, factor=4.0, count=14):
    """Fixed log-scale bucket upper bounds: ``start * factor**i``.

    The default spans 1us .. ~67s — wide enough for a single XLA op
    dispatch and a cold first-step compile in the same histogram."""
    return tuple(start * factor ** i for i in range(count))


def _labels_key(labels):
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = None

    def __init__(self, name, labels=()):
        self.name = name
        self.labels = OrderedDict(labels)
        self._lock = threading.Lock()

    def to_dict(self):
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic count (reference ``STAT_ADD``)."""

    kind = "counter"

    def __init__(self, name, labels=()):
        _Metric.__init__(self, name, labels)
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError("Counter.inc(%r): counters only go up — "
                             "use a Gauge" % (n,))
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _reset_value(self):
        with self._lock:
            self._value = 0

    def to_dict(self):
        return {"kind": self.kind, "value": self._value}


class Gauge(_Metric):
    """Point-in-time value (reference ``STAT_RESET`` on a stat)."""

    kind = "gauge"

    def __init__(self, name, labels=()):
        _Metric.__init__(self, name, labels)
        self._value = 0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    def track(self, n=1):
        """Context manager: ``inc(n)`` on entry, ``dec(n)`` on exit —
        the in-flight/occupancy idiom (e.g. a prefetch thread holds the
        gauge at 1 while its pull is outstanding). Exception-safe, so a
        crashed worker never leaves the gauge pinned high."""
        return _GaugeTracker(self, n)

    @property
    def value(self):
        return self._value

    def _reset_value(self):
        with self._lock:
            self._value = 0

    def to_dict(self):
        return {"kind": self.kind, "value": self._value}


class _GaugeTracker:
    def __init__(self, gauge, n):
        self._gauge = gauge
        self._n = n

    def __enter__(self):
        self._gauge.inc(self._n)
        return self._gauge

    def __exit__(self, *exc):
        self._gauge.dec(self._n)
        return False


class Histogram(_Metric):
    """Fixed log-scale buckets + sum/count/min/max. ``observe()`` is a
    bisect + two adds under the metric lock — cheap enough for the
    executor hot path."""

    kind = "histogram"

    def __init__(self, name, labels=(), buckets=None):
        _Metric.__init__(self, name, labels)
        self.buckets = tuple(sorted(buckets if buckets is not None
                                    else default_buckets()))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def time(self):
        """Context manager observing the elapsed seconds of its body."""
        return _HistogramTimer(self)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """Approximate q-quantile (0 <= q <= 1) interpolated from the
        fixed buckets (the ``histogram_quantile`` estimate a Prometheus
        scrape would compute), clamped to the observed min/max so tight
        distributions don't report a whole bucket's width of error.
        Values landing in the +Inf overflow bucket report the observed
        max. Returns None while the histogram is empty."""
        if not 0.0 <= float(q) <= 1.0:
            raise ValueError("quantile q must be in [0, 1], got %r" % (q,))
        with self._lock:
            total = self._count
            counts = list(self._counts)
            mn, mx = self._min, self._max
        if not total:
            return None
        target = float(q) * total
        if target <= 0:
            return mn
        acc, prev = 0, 0.0
        for le, c in zip(self.buckets, counts):
            if c and acc + c >= target:
                lo = prev if mn is None else max(prev, min(mn, le))
                hi = le if mx is None else max(lo, min(le, mx))
                return lo + (hi - lo) * (target - acc) / c
            acc += c
            prev = le
        return mx  # overflow bucket: the best bounded answer available

    def bucket_counts(self):
        """Raw per-bucket counts (NOT cumulative), one per bound plus
        the trailing +Inf overflow slot — the mergeable form: two
        processes' vectors add element-wise and the merged ``quantile``
        is exact over the shared bounds (telemetry/aggregate.py)."""
        with self._lock:
            return list(self._counts)

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count), ...] ending with +Inf —
        the Prometheus histogram series shape."""
        out, acc = [], 0
        with self._lock:
            counts = list(self._counts)
        for le, c in zip(self.buckets, counts):
            acc += c
            out.append((le, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def _reset_value(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = None
            self._max = None

    def to_dict(self):
        return {"kind": self.kind, "count": self._count,
                "sum": self._sum, "min": self._min, "max": self._max,
                "buckets": [[le, c] for le, c
                            in self.cumulative_buckets()]}


class _HistogramTimer:
    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._hist.observe(time.perf_counter() - self._t0)
        return False


def _get_or_create(cls, name, help, labels, **kw):
    key = (name, _labels_key(labels))
    with _LOCK:
        m = _REGISTRY.get(key)
        if m is not None:
            if m.kind != cls.kind:
                raise ValueError(
                    "metric %r already registered as a %s (wanted %s)"
                    % (name, m.kind, cls.kind))
            return m
        known = _KINDS.get(name)
        if known is not None and known[0] != cls.kind:
            raise ValueError(
                "metric %r already registered as a %s (wanted %s)"
                % (name, known[0], cls.kind))
        m = cls(name, labels=_labels_key(labels), **kw)
        _REGISTRY[key] = m
        if known is None or (help and not known[1]):
            _KINDS[name] = (cls.kind, help or (known[1] if known else ""))
        return m


def counter(name, help="", labels=None):
    """Get-or-create the Counter for (name, labels)."""
    return _get_or_create(Counter, name, help, labels)


def gauge(name, help="", labels=None):
    """Get-or-create the Gauge for (name, labels)."""
    return _get_or_create(Gauge, name, help, labels)


def histogram(name, help="", labels=None, buckets=None):
    """Get-or-create the Histogram for (name, labels). ``buckets`` is
    honored on first creation only (series of one name share bounds)."""
    return _get_or_create(Histogram, name, help, labels, buckets=buckets)


def get_metric(name, labels=None):
    """The registered metric, or None."""
    return _REGISTRY.get((name, _labels_key(labels)))


def sum_labeled(name):
    """Sum a counter/gauge named ``name`` across every label set it was
    registered under (0.0 when none exist) — the fleet/bench roll-up for
    per-model and per-replica series."""
    with _LOCK:
        return sum(m.value for (n, _), m in _REGISTRY.items()
                   if n == name and hasattr(m, "value"))


def all_metrics():
    """Snapshot list of registered metrics (registration order)."""
    with _LOCK:
        return list(_REGISTRY.values())


def reset():
    """Zero every metric's VALUE in place. Instances stay registered, so
    module-level references held by the executor/reader keep working —
    this is the test-isolation hook."""
    for m in all_metrics():
        m._reset_value()


# -- exposition ---------------------------------------------------------------

def dump_json():
    """{name: [{"labels": {...}, <metric fields>}, ...]} — the bench.py
    embedding format."""
    out = OrderedDict()
    for m in all_metrics():
        d = m.to_dict()
        d["labels"] = dict(m.labels)
        out.setdefault(m.name, []).append(d)
    return out


def snapshot(proc=None):
    """Raw mergeable snapshot of the whole registry — the blob each
    fleet process pushes to the coordination KV for cross-process
    aggregation (``telemetry/aggregate.merge``). Histograms ship their
    bucket BOUNDS and raw per-bucket counts so the merge can verify the
    grids match and add them element-wise; gauges ride with the
    snapshot timestamp so the merge can apply last-write-wins."""
    import time

    mets = []
    for m in all_metrics():
        rec = {"name": m.name, "kind": m.kind,
               "labels": dict(m.labels),
               "help": _KINDS.get(m.name, (m.kind, ""))[1]}
        if isinstance(m, Histogram):
            with m._lock:
                rec.update(bounds=list(m.buckets),
                           counts=list(m._counts), sum=m._sum,
                           count=m._count, min=m._min, max=m._max)
        else:
            rec["value"] = m.value
        mets.append(rec)
    return {"proc": proc, "pid": os.getpid(), "ts": time.time(),
            "metrics": mets}


_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _prom_name(name):
    if _NAME_OK.match(name):
        return name
    return re.sub(r"[^a-zA-Z0-9_:]", "_",
                  name if not name[:1].isdigit() else "_" + name)


def _prom_labels(labels, extra=()):
    items = list(labels.items()) + list(extra)
    if not items:
        return ""
    def esc(v):
        return str(v).replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")
    return "{%s}" % ",".join('%s="%s"' % (_prom_name(k), esc(v))
                             for k, v in items)


def _prom_num(v):
    if v is None:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def dump_prometheus(dst=None, metrics=None, kinds=None):
    """Render every metric in Prometheus text exposition format 0.0.4
    and return the text. ``dst``: None, a path string, or a writable
    stream. Series are grouped per name under one HELP/TYPE header,
    sorted for deterministic output (golden-testable).

    ``metrics``/``kinds`` render an EXPLICIT metric list instead of the
    process registry — the fleet-merged view (telemetry/aggregate.py)
    reuses this renderer so the aggregated dump cannot drift from the
    per-process format."""
    by_name = OrderedDict()
    for m in (all_metrics() if metrics is None else metrics):
        by_name.setdefault(m.name, []).append(m)
    kind_map = _KINDS if kinds is None else kinds
    lines = []
    for name in sorted(by_name):
        pname = _prom_name(name)
        kind, help = kind_map.get(name, (by_name[name][0].kind, ""))
        if help:
            lines.append("# HELP %s %s"
                         % (pname, help.replace("\\", "\\\\")
                            .replace("\n", "\\n")))
        lines.append("# TYPE %s %s" % (pname, kind))
        for m in sorted(by_name[name], key=lambda m: tuple(m.labels.items())):
            if isinstance(m, Histogram):
                for le, c in m.cumulative_buckets():
                    lines.append("%s_bucket%s %d" % (
                        pname,
                        _prom_labels(m.labels, [("le", _prom_num(le))]), c))
                lines.append("%s_sum%s %s" % (pname,
                                              _prom_labels(m.labels),
                                              _prom_num(m._sum)))
                lines.append("%s_count%s %d" % (pname,
                                                _prom_labels(m.labels),
                                                m._count))
            else:
                lines.append("%s%s %s" % (pname, _prom_labels(m.labels),
                                          _prom_num(m.value)))
    text = "\n".join(lines) + ("\n" if lines else "")
    if dst is not None:
        if hasattr(dst, "write"):
            dst.write(text)
        else:
            with open(dst, "w") as f:
                f.write(text)
    return text


# -- atexit dump --------------------------------------------------------------

def _dump_to_path(path):
    if path.endswith(".json"):
        with open(path, "w") as f:
            json.dump(dump_json(), f, indent=1)
    else:
        dump_prometheus(path)
    return path


def _atexit_dump():
    path = os.environ.get(ENV_DUMP)
    if not path:
        return
    try:
        _dump_to_path(path)
    except OSError:
        pass  # interpreter teardown: never raise


atexit.register(_atexit_dump)
