"""Profiler — reference ``python/paddle/fluid/profiler.py:228`` +
``platform/profiler.h:81,166`` (RecordEvent, Enable/DisableProfiler,
per-event summary table, chrome timeline via ``tools/timeline.py``).

TPU-native: under XLA the per-op host interpreter is gone, so host-side
events are step/section-level (``RecordEvent`` contexts + Executor.run
timings hooked here), and the DEVICE timeline comes from ``jax.profiler``
traces (XPlane — openable in TensorBoard/Perfetto, the chrome-trace
analogue). The summary table keeps the reference's shape:
Event / Calls / Total / Min / Max / Ave / Ratio.
"""

import contextlib
import time
from collections import OrderedDict, deque

from . import monitor as _monitor

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "export_chrome_tracing", "dropped_span_count",
           "RecordEvent", "cuda_profiler", "npu_profiler"]

_enabled = False
_events = OrderedDict()  # name -> [calls, total, min, max]
_trace_dir = None
_MAX_SPANS = 200_000
# (name, t_end, dur) ring for the chrome timeline. A RING, not a
# capped list: on overflow the OLDEST span is evicted, so the buffer
# always holds the last seconds of the run — the flight recorder's
# postmortem window — instead of the first seconds of warm-up.
_spans = deque(maxlen=_MAX_SPANS)
_dropped = [0]           # spans evicted past _MAX_SPANS
# perf_counter has an arbitrary epoch; anchor it to unix time once so
# host spans land on the same clock as device XPlane timestamps
_EPOCH_ANCHOR = (time.perf_counter(), time.time())

_M_DROPPED = _monitor.counter(
    "profiler_dropped_spans_total",
    help="host spans evicted from the full span ring (oldest-out; the "
         "ring keeps the newest _MAX_SPANS)")
# one monitor histogram series per event name, cached so the per-record
# cost is a dict hit rather than a registry lookup
_mon_hists = {}


def _mon_hist(name):
    h = _mon_hists.get(name)
    if h is None:
        h = _monitor.histogram(
            "profiler_event_seconds",
            help="host RecordEvent/Executor span durations",
            labels={"event": name})
        _mon_hists[name] = h
    return h


def now():
    return time.perf_counter()


def dropped_span_count():
    """Spans evicted since the last reset_profiler() (ring overflow —
    the evicted spans are the OLDEST; the ring keeps the newest)."""
    return _dropped[0]


def _record(name, seconds):
    if not _enabled:
        return
    e = _events.get(name)
    if e is None:
        _events[name] = [1, seconds, seconds, seconds]
    else:
        e[0] += 1
        e[1] += seconds
        e[2] = min(e[2], seconds)
        e[3] = max(e[3], seconds)
    _mon_hist(name).observe(seconds)
    if len(_spans) == _spans.maxlen:   # appending evicts the oldest
        _dropped[0] += 1
        _M_DROPPED.inc()
    _spans.append((name, time.perf_counter(), seconds))


class RecordEvent:
    """RAII host event (reference platform/profiler.h:81)."""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _record(self.name, time.perf_counter() - self._t0)
        return False


def record_event(name):
    return RecordEvent(name)


def is_profiler_enabled():
    return _enabled


def start_profiler(state="All", tracer_option="Default", trace_dir=None):
    """Enable host-event collection; with ``trace_dir`` also start a
    jax.profiler device trace (the CUPTI/DeviceTracer analogue)."""
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if trace_dir is not None:
        import jax

        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path=None, timeline_path=None,
                  silent=False):
    """Disable collection, print the summary table (suppressed with
    ``silent`` — the dygraph gperf route wants collection without the
    stdout table), optionally write it to ``profile_path``, stop the
    device trace if one is running, and — with ``timeline_path`` —
    export a chrome://tracing JSON (the reference's ``tools/timeline.py``
    output, host events + any captured device ops)."""
    global _enabled, _trace_dir
    _enabled = False
    trace_dir = _trace_dir
    if _trace_dir is not None:
        import jax

        jax.profiler.stop_trace()
        _trace_dir = None
    report = summary(sorted_key)
    if not silent:
        print(report)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    if timeline_path:
        export_chrome_tracing(timeline_path, trace_dir=trace_dir)
    return report


def export_chrome_tracing(path, trace_dir=None):
    """Write a chrome://tracing JSON: host RecordEvent/Executor spans as
    pid 0, and — when a jax.profiler trace was captured and the xplane
    proto is importable — the device's XLA-op timeline as pid 1.
    Reference ``tools/timeline.py`` emits the same format from its
    profile protos."""
    import glob
    import json

    pc0, unix0 = _EPOCH_ANCHOR
    events = []
    for name, t_end, dur in _spans:
        start_unix = (t_end - dur) - pc0 + unix0
        events.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                       "ts": start_unix * 1e6, "dur": dur * 1e6,
                       "cat": "host"})
    if trace_dir:
        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2

            files = glob.glob(trace_dir + "/**/*.xplane.pb",
                              recursive=True)
            if files:
                xs = xplane_pb2.XSpace()
                with open(sorted(files)[-1], "rb") as f:
                    xs.ParseFromString(f.read())
                for plane in xs.planes:
                    if "/device:" not in plane.name:
                        continue
                    md = plane.event_metadata
                    for line in plane.lines:
                        if line.name != "XLA Ops":
                            continue
                        for ev in line.events:
                            nm = md[ev.metadata_id].name.split(" = ")[0]
                            events.append({
                                "name": nm.lstrip("%")[:120], "ph": "X",
                                "pid": 1, "tid": int(line.id or 0),
                                "ts": (line.timestamp_ns +
                                       ev.offset_ps / 1e3) / 1e3,
                                "dur": ev.duration_ps / 1e6,
                                "cat": "device"})
        except Exception as e:  # host spans still export
            events.append({"name": "xplane-convert-failed: %r" % (e,),
                           "ph": "i", "pid": 1, "tid": 0, "ts": 0,
                           "s": "g"})
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "host"}},
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": "device (XLA ops)"}},
            # how many host spans the buffer dropped — a trace that hit
            # _MAX_SPANS is TRUNCATED and must say so
            {"name": "dropped_spans", "ph": "M", "pid": 0,
             "args": {"count": _dropped[0]}}]
    with open(path, "w") as f:
        json.dump({"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}, f)
    return path


def reset_profiler():
    global _spans
    _events.clear()
    if _spans.maxlen != _MAX_SPANS:
        # _MAX_SPANS was adjusted after import (tests shrink it); the
        # ring's maxlen is fixed at construction, so rebuild
        _spans = deque(maxlen=_MAX_SPANS)
    else:
        _spans.clear()
    _dropped[0] = 0


def summary(sorted_key=None):
    """Reference-shaped table: Event Calls Total Min Max Ave Ratio."""
    total_all = sum(e[1] for e in _events.values()) or 1e-12
    rows = []
    for name, (calls, total, mn, mx) in _events.items():
        rows.append((name, calls, total, mn, mx, total / calls,
                     total / total_all))
    if sorted_key in ("total", "calls", "max", "min", "ave"):
        idx = {"calls": 1, "total": 2, "min": 3, "max": 4, "ave": 5}[sorted_key]
        rows.sort(key=lambda r: r[idx], reverse=sorted_key != "min")
    lines = ["------------------------->  Profiling Report  "
             "<-------------------------", "",
             "%-40s %8s %12s %12s %12s %12s %8s" % (
                 "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)",
                 "Ave(ms)", "Ratio")]
    for name, calls, total, mn, mx, ave, ratio in rows:
        lines.append("%-40s %8d %12.4f %12.4f %12.4f %12.4f %7.2f%%" % (
            name[:40], calls, total * 1e3, mn * 1e3, mx * 1e3, ave * 1e3,
            ratio * 100))
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             tracer_option="Default", trace_dir=None, timeline_path=None):
    """Reference ``fluid.profiler.profiler`` context manager."""
    reset_profiler()
    start_profiler(state, tracer_option, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path, timeline_path)


@contextlib.contextmanager
def cuda_profiler(*a, **kw):
    """Device traces come from jax.profiler; kept for API parity."""
    yield


npu_profiler = cuda_profiler
