"""ParamAttr: per-parameter configuration (reference
``python/paddle/fluid/param_attr.py``)."""


class ParamAttr:
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=False,
        shard=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        # tensor-parallel sharding spec: a tuple with one entry per param
        # dim, each a mesh axis name or None (e.g. (None, "tp") = column-
        # parallel). Consumed by CompiledProgram's GSPMD wrap: the param is
        # laid out over the mesh and XLA inserts the TP collectives.
        self.shard = tuple(shard) if shard is not None else None

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        # an Initializer instance
        return ParamAttr(initializer=arg)


WeightNormParamAttr = ParamAttr
