"""Gradient clipping (reference ``python/paddle/fluid/clip.py``):
by value, by norm, by global norm; attached per-param or globally."""

from . import framework
from .framework import Variable

__all__ = [
    "set_gradient_clip", "ErrorClipByValue", "GradientClipByValue",
    "GradientClipByNorm", "GradientClipByGlobalNorm",
    "append_gradient_clip_ops",
]

_GRADIENT_CLIP_ATTR = "@grad_clip@"


class BaseErrorClipAttr:
    pass


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class BaseGradientClipAttr:
    def _process(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _process(self, params_grads):
        from .layers import nn

        return [(p, nn.clip(g, self.min, self.max)) for p, g in params_grads]


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        from .layers import nn

        return [(p, nn.clip_by_norm(g, self.clip_norm)) for p, g in params_grads]


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        from .layers import nn, ops, tensor

        sq_sums = [nn.reduce_sum(ops.square(g)) for _, g in params_grads]
        stacked = nn.sum([nn.reshape(s, [1]) for s in sq_sums]) if len(sq_sums) > 1 \
            else nn.reshape(sq_sums[0], [1])
        global_norm = ops.sqrt(stacked)
        clip_var = tensor.fill_constant([1], "float32", self.clip_norm)
        scale = nn.elementwise_div(
            clip_var, nn.elementwise_max(global_norm, clip_var))
        return [(p, nn.elementwise_mul(g, scale)) for p, g in params_grads]


_global_clip = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip
    if param_list:
        for p in param_list:
            if isinstance(p, str):
                p = framework.default_main_program().global_block().var(p)
            p._grad_clip = clip


def append_gradient_clip_ops(params_grads):
    # per-param clip attr wins; else the global clip
    clipped = []
    todo_global = []
    for p, g in params_grads:
        if g is not None and getattr(g, "type", "lod_tensor") == "selected_rows":
            # sparse grads bypass clipping (reference clips dense only;
            # clipping values alone would mis-scale duplicate rows)
            clipped.append((p, g))
            continue
        attr = getattr(p, "_grad_clip", None)
        if attr is not None:
            clipped.extend(attr._process([(p, g)]))
        else:
            todo_global.append((p, g))
    if todo_global:
        if _global_clip is not None:
            clipped.extend(_global_clip._process(todo_global))
        else:
            clipped.extend(todo_global)
    return clipped
