"""Dygraph gradient clipping strategies.

Parity: reference ``fluid/dygraph_grad_clip.py`` (GradClipBase:34,
GradClipByValue:46, GradClipByNorm:120, GradClipByGlobalNorm:191).
Passed as ``optimizer.minimize(loss, grad_clip=...)`` in dygraph mode;
the optimizer hands the clip the full ``[(param, grad), ...]`` list
after the backward pass (grads are device arrays) and applies the
returned grads. TPU note: each strategy is a handful of jnp ops that
XLA fuses into the per-parameter update dispatch; the global-norm
variant reduces once over all grads, exactly like the static
``GradientClipByGlobalNorm`` pass.
"""

import jax.numpy as jnp

__all__ = [
    "GradClipBase", "GradClipByValue", "GradClipByNorm",
    "GradClipByGlobalNorm",
]


class GradClipBase(object):
    def _clip(self, para_and_grad):
        raise NotImplementedError

    def __call__(self, para_and_grad):
        return self._clip(para_and_grad)


class GradClipByValue(GradClipBase):
    """Elementwise clamp into [min_value, max_value]. With one argument,
    the range is symmetric: [-min_value, min_value] (reference :92)."""

    def __init__(self, min_value, max_value=None):
        if max_value is None:
            min_value, max_value = -abs(min_value), abs(min_value)
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def __str__(self):
        return "ClipByValue, min=%f, max=%f" % (self.min_value,
                                                self.max_value)

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, jnp.clip(g, self.min_value, self.max_value)))
        return out


class GradClipByNorm(GradClipBase):
    """Per-tensor L2-norm clip: g * clip_norm / max(norm(g), clip_norm)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __str__(self):
        return "ClipByNorm, clip_norm=%f" % self.clip_norm

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
            out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out


class GradClipByGlobalNorm(GradClipBase):
    """Joint clip by the global norm over ALL grads:
    g_i * clip_norm / max(global_norm, clip_norm), with
    global_norm = sqrt(sum_i ||g_i||^2) (reference :191)."""

    def __init__(self, max_global_norm):
        self.max_global_norm = float(max_global_norm)

    def __str__(self):
        return "ClipByGlobalNorm, max_global_norm=%f" % self.max_global_norm

    def _clip(self, para_and_grad):
        grads = [g for _, g in para_and_grad if g is not None]
        if not grads:
            return list(para_and_grad)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        global_norm = jnp.sqrt(sq)
        scale = self.max_global_norm / jnp.maximum(global_norm,
                                                   self.max_global_norm)
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out
