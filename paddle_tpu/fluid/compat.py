"""Program/op compatibility checks (reference ``framework/version.h`` +
``framework/op_compatible_info.{h,cc}``: a loaded ProgramDesc is checked
against the running framework before execution).

TPU-native form: "compatible" means (a) the serialized program version is
one this build understands, and (b) every op type in the program has a
registered XLA lowering rule — the analogue of the reference's
kernel-availability check."""

from .registry import registry as _op_registry

__all__ = ["PROGRAM_VERSION", "is_program_version_supported",
           "check_program_compatible", "CompatibleInfo"]

# Serialized-program versions this build can execute. Version 1 is the only
# format so far (core/framework.proto `version`).
PROGRAM_VERSION = 1
_SUPPORTED_VERSIONS = (1,)


def is_program_version_supported(version):
    return version in _SUPPORTED_VERSIONS


class CompatibleInfo:
    """Result of a compatibility scan (reference OpCompatibleType)."""

    COMPATIBLE = "compatible"
    UNSUPPORTED_VERSION = "unsupported_version"
    UNDEFINED_OP = "undefined_op"

    def __init__(self, status, detail=""):
        self.status = status
        self.detail = detail

    def __bool__(self):
        return self.status == self.COMPATIBLE

    def __repr__(self):
        return "CompatibleInfo(%s%s)" % (
            self.status, ": " + self.detail if self.detail else "")


# Op types consumed structurally by the executor/autodiff rather than via a
# lowering rule. (save/load have real lowerings in ops/creation.py; the
# listen_and_serv pair is run specially by the Executor as host serving
# loops, executor.py.)
_STRUCTURAL_OPS = frozenset({"feed", "fetch", "autodiff",
                             "py_func", "listen_and_serv",
                             "fl_listen_and_serv"})


def check_program_compatible(program, version=None):
    """Scan ``program`` (a Program or a desc dict from proto_io) and return
    a CompatibleInfo. Raise nothing — callers decide."""
    if version is None and isinstance(program, dict):
        version = program.get("version", PROGRAM_VERSION)
    if version is not None and not is_program_version_supported(version):
        return CompatibleInfo(CompatibleInfo.UNSUPPORTED_VERSION,
                              "program version %s (supported: %s)"
                              % (version, list(_SUPPORTED_VERSIONS)))
    known = set(_op_registry.types())

    def _unknown(t):
        # *_grad op types are consumed by the autodiff replay, not by a
        # per-op lowering rule — exempt in both scan paths. A missing or
        # malformed type is "unknown" (never raise: see contract above).
        t = t if isinstance(t, str) else "<missing type>"
        return (t not in known and t not in _STRUCTURAL_OPS
                and not t.endswith("_grad"))

    missing = set()
    if isinstance(program, dict):
        types = (op.get("type") for blk in program.get("blocks", [])
                 for op in blk.get("ops", []))
    else:
        types = (op.type for blk in program.blocks for op in blk.ops)
    for t in types:
        if _unknown(t):
            missing.add(t if isinstance(t, str) else "<missing type>")
    if missing:
        return CompatibleInfo(CompatibleInfo.UNDEFINED_OP,
                              "no lowering for: %s" % ", ".join(sorted(missing)))
    return CompatibleInfo(CompatibleInfo.COMPATIBLE)
