"""DataFeeder: converts python/numpy minibatch data into feed dicts
(reference ``python/paddle/fluid/data_feeder.py``)."""

import numpy as np

from .framework import Variable

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = [
            v.name if isinstance(v, Variable) else v for v in feed_list
        ]
        self.feed_vars = [v for v in feed_list if isinstance(v, Variable)]

    def feed(self, iterable):
        """iterable: list of samples; each sample is a tuple aligned with
        feed_list. Batches samples along dim 0."""
        columns = [[] for _ in self.feed_names]
        for sample in iterable:
            for i, item in enumerate(sample):
                columns[i].append(np.asarray(item))
        out = {}
        for name, var, col in zip(self.feed_names, self.feed_vars, columns):
            arr = np.stack(col)
            want = var.shape
            # honor declared trailing shape, e.g. label (N,1) vs samples ()
            if want and len(want) == arr.ndim + 1 and want[-1] == 1:
                arr = arr[..., None]
            if want and len(want) == arr.ndim and all(
                w > 0 for w in want[1:]
            ):
                try:
                    arr = arr.reshape((arr.shape[0],) + tuple(want[1:]))
                except ValueError:
                    pass
            out[name] = arr.astype(var.dtype)
        return out
