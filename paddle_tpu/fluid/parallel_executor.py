"""ParallelExecutor — the reference's pre-CompiledProgram multi-device
API (``python/paddle/fluid/parallel_executor.py:28``; internally the SSA
graph executor, ``details/fast_threaded_ssa_graph_executor.cc``).

TPU-native: multi-device execution is GSPMD over a mesh, so this class
is a faithful API adapter binding ``CompiledProgram.with_data_parallel``
to an Executor + scope — exactly the migration the reference itself
performs (its ParallelExecutor constructs a CompiledProgram under the
hood in later versions). ``use_cuda`` is accepted for signature parity
and ignored (placement is the JAX backend's)."""

from . import compiler, framework
from .executor import Executor, global_scope

__all__ = ["ParallelExecutor"]


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        if int(num_trainers) > 1:
            # multi-trainer PE in the reference wires NCCL across nodes;
            # here cross-process DP goes through fleet/jax.distributed
            # (distributed/env.py) — refusing beats silent divergence
            raise ValueError(
                "ParallelExecutor(num_trainers>1) is not supported: use "
                "fleet collective mode / paddle_tpu.distributed for "
                "multi-process data parallelism")
        self._main = main_program or framework.default_main_program()
        self._compiled = compiler.CompiledProgram(
            self._main, build_strategy).with_data_parallel(
            loss_name=loss_name, exec_strategy=exec_strategy)
        self._exe = Executor()
        if share_vars_from is not None:
            if scope is not None and scope is not share_vars_from._scope:
                raise ValueError(
                    "pass either share_vars_from or scope, not both — "
                    "share_vars_from reuses the other executor's scope")
            # reference semantics: reuse the training PE's variables
            # (e.g. a test-program PE sharing weights)
            self._scope = share_vars_from._scope
        else:
            self._scope = scope or global_scope()

    @property
    def device_count(self):
        import jax

        return len(jax.devices())

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        """Reference signature: fetch_list FIRST. ``feed_dict`` is the
        deprecated alias the reference still accepts. A per-device feed
        (list of dicts, the reference's explicit-placement form) is
        accepted by concatenating along dim 0 — GSPMD re-shards the
        global batch itself."""
        if feed is None:
            feed = feed_dict
        if isinstance(feed, (list, tuple)):
            import numpy as np

            merged = {}
            for k in feed[0]:
                merged[k] = np.concatenate(
                    [np.asarray(d[k]) for d in feed], axis=0)
            feed = merged
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=list(fetch_list),
                             scope=self._scope,
                             return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        """Reference API: frees per-device local scopes between
        iterations. GSPMD holds no per-device scopes — nothing to drop."""
