"""Locate distributed embedding tables in a Program.

Parity: reference ``fluid/distribute_lookup_table.py`` (the transpiler/
fleet helper that finds the single distributed ``lookup_table`` and its
ids/outputs). Here the distributed embedding lowers to the
``distributed_lookup_table`` op (``ops/distributed_ops.py``) whose table
lives in the host PS store keyed by the ``table_name`` attr, so the
search matches on that op type.
"""

LOOKUP_TABLE_TYPE = "distributed_lookup_table"

__all__ = [
    "find_distributed_lookup_table",
    "find_distributed_lookup_table_inputs",
    "find_distributed_lookup_table_outputs",
]


def _table_of(op):
    return op.attr("table_name")


def find_distributed_lookup_table(program):
    """The single distributed table's name, or None. More than one
    distinct table raises (same contract as the reference — the PS
    split path assumes one)."""
    table_name = None
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE:
            name = _table_of(op)
            if table_name is None:
                table_name = name
            elif table_name != name:
                raise RuntimeError(
                    "all distributed lookup_table ops should share one "
                    "table; found %r and %r" % (table_name, name))
    return table_name


def find_distributed_lookup_table_inputs(program, table_name):
    """Ids variables feeding the distributed table's lookups."""
    local_vars = program.current_block().vars
    inputs = []
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and _table_of(op) == table_name:
            inputs.extend(local_vars[name] for name in op.input("Ids"))
    return inputs


def find_distributed_lookup_table_outputs(program, table_name):
    """Output variables produced by the distributed table's lookups."""
    local_vars = program.current_block().vars
    outputs = []
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and _table_of(op) == table_name:
            outputs.extend(local_vars[name] for name in op.output("Out"))
    return outputs
