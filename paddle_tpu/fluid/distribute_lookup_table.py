"""DEPRECATED — folded into :mod:`paddle_tpu.embedding.lookup`.

Parity: reference ``fluid/distribute_lookup_table.py`` (the transpiler/
fleet helper that finds the single distributed ``lookup_table`` and its
ids/outputs). The sparse embedding engine is now the one entry point for
sparse-lookup introspection — it knows about the engine's own op types
(``embedding_lookup``, ``host_embedding_lookup``) in addition to the
legacy PS shim matched here. Import from ``paddle_tpu.embedding.lookup``;
this module stays as a thin re-export so existing callers keep working,
with a :class:`DeprecationWarning` per call.
"""

import warnings

LOOKUP_TABLE_TYPE = "distributed_lookup_table"

__all__ = [
    "find_distributed_lookup_table",
    "find_distributed_lookup_table_inputs",
    "find_distributed_lookup_table_outputs",
]


def _deprecated(name):
    # lazy import: fluid/__init__ imports this module, so pulling the
    # engine in at module level would cycle through a half-built fluid
    from ..embedding import lookup

    warnings.warn(
        "fluid.distribute_lookup_table.%s is deprecated; use "
        "paddle_tpu.embedding.lookup.%s instead" % (name, name),
        DeprecationWarning, stacklevel=3)
    return lookup


def find_distributed_lookup_table(program):
    """See :func:`paddle_tpu.embedding.lookup.find_distributed_lookup_table`."""
    return _deprecated(
        "find_distributed_lookup_table").find_distributed_lookup_table(program)


def find_distributed_lookup_table_inputs(program, table_name):
    """See :func:`paddle_tpu.embedding.lookup.find_distributed_lookup_table_inputs`."""
    return _deprecated(
        "find_distributed_lookup_table_inputs"
    ).find_distributed_lookup_table_inputs(program, table_name)


def find_distributed_lookup_table_outputs(program, table_name):
    """See :func:`paddle_tpu.embedding.lookup.find_distributed_lookup_table_outputs`."""
    return _deprecated(
        "find_distributed_lookup_table_outputs"
    ).find_distributed_lookup_table_outputs(program, table_name)
