class EOFException(Exception):
    """py_reader queue drained (reference ``fluid.core.EOFException``):
    the loop-shape contract is `reader.start(); while True: exe.run()`
    until this raises, then `reader.reset()` for the next epoch."""




def memory_stats(device_index=0):
    """Device memory counters (reference memory/stat.h STAT_* surface):
    {bytes_in_use, peak_bytes_in_use, bytes_limit, ...} from the XLA
    allocator; {} on backends that do not report (CPU)."""
    import jax

    dev = jax.devices()[device_index]
    stats = getattr(dev, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}


def max_memory_allocated(device_index=0):
    """Peak bytes in use on the device (0 when the backend has no
    counters)."""
    return int(memory_stats(device_index).get("peak_bytes_in_use", 0))


def memory_allocated(device_index=0):
    """Current bytes in use on the device."""
    return int(memory_stats(device_index).get("bytes_in_use", 0))


# host-side tensor containers (reference binds these from C++ core)
from ..lod import LoDTensor, LoDTensorArray  # noqa: F401,E402
