"""desc-dict <-> protobuf bytes conversion for Program serialization.

Parity: the reference serializes ``ProgramDesc`` protobuf directly
(``program_desc.h:30``); here the in-memory IR is plain Python and this
module is the (de)serialization boundary. Loads are version-gated
(reference ``framework/version.h`` IsProgramVersionSupported) and
op-compat checked (``op_compatible_info.cc``): a program written by a
newer framework, or using op types this build doesn't register, fails
loudly at load instead of mid-execution.
"""

from . import framework_pb2 as pb

# Version + op-compat POLICY lives in fluid/compat.py (PROGRAM_VERSION,
# is_program_version_supported, check_program_compatible with its
# structural/_grad exemptions) — this module only ENFORCES it at the
# deserialization boundary so raw loads (Program.parse_from_string)
# cannot bypass the gate the io.py loader applies.


class ProgramCompatError(RuntimeError):
    """Load-gate failure; ``status`` is the CompatibleInfo status
    (``unsupported_version`` or ``undefined_op``) so callers can offer
    the right remedy without string-matching."""

    def __init__(self, message, status=""):
        super().__init__(message)
        self.status = status


class ProgramVersionError(ProgramCompatError):
    pass


def _attr_to_pb(a, value):
    if isinstance(value, bool):
        a.b = value
    elif isinstance(value, int):
        a.i = value
    elif isinstance(value, float):
        a.f = value
    elif isinstance(value, str):
        a.s = value
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, bool) for v in value) and value:
            a.ints.val.extend(int(v) for v in value)
        elif all(isinstance(v, int) for v in value):
            a.ints.val.extend(value)
        elif all(isinstance(v, (int, float)) for v in value):
            a.floats.val.extend(float(v) for v in value)
        else:
            a.strings.val.extend(str(v) for v in value)
    elif value is None:
        a.s = "\0__none__"
    else:
        a.s = "\0__repr__" + repr(value)


def _attr_from_pb(a):
    kind = a.WhichOneof("value")
    if kind == "i":
        return int(a.i)
    if kind == "f":
        return float(a.f)
    if kind == "s":
        if a.s == "\0__none__":
            return None
        if a.s.startswith("\0__repr__"):
            import ast

            try:
                return ast.literal_eval(a.s[len("\0__repr__"):])
            except (ValueError, SyntaxError):
                return a.s
        return a.s
    if kind == "b":
        return bool(a.b)
    if kind == "ints":
        return [int(v) for v in a.ints.val]
    if kind == "floats":
        return [float(v) for v in a.floats.val]
    if kind == "strings":
        return list(a.strings.val)
    return None


def program_to_bytes(desc):
    p = pb.ProgramDesc()
    from ..compat import PROGRAM_VERSION

    p.version = desc.get("version", PROGRAM_VERSION)
    p.random_seed = desc.get("random_seed", 0)
    for k, v in desc.get("param_grad_map", {}).items():
        p.param_grad_map[k] = v
    p.feed_names.extend(desc.get("feed_names", []))
    p.fetch_names.extend(desc.get("fetch_names", []))
    for bdesc in desc["blocks"]:
        b = p.blocks.add()
        b.idx = bdesc["idx"]
        b.parent_idx = bdesc.get("parent_idx", -1)
        for vdesc in bdesc["vars"]:
            v = b.vars.add()
            v.name = vdesc["name"]
            v.shape.extend(int(s) for s in vdesc["shape"])
            v.dtype = vdesc["dtype"]
            v.persistable = vdesc.get("persistable", False)
            v.stop_gradient = vdesc.get("stop_gradient", False)
            v.is_data = vdesc.get("is_data", False)
            v.is_parameter = vdesc.get("is_parameter", False)
            v.trainable = vdesc.get("trainable", False)
        for odesc in bdesc["ops"]:
            o = b.ops.add()
            o.type = odesc["type"]
            for slot, args in odesc["inputs"].items():
                s = o.inputs.add()
                s.slot = slot
                s.args.extend(args)
            for slot, args in odesc["outputs"].items():
                s = o.outputs.add()
                s.slot = slot
                s.args.extend(args)
            for k, v in odesc["attrs"].items():
                _attr_to_pb(o.attrs[k], v)
    # deterministic: map fields (op attrs, param_grad_map) otherwise
    # serialize in per-process hash order, so the same program would
    # hash to a different compile-cache key after every restart
    return p.SerializeToString(deterministic=True)


def program_from_bytes(data, check=True):
    """Parse + validate against fluid.compat (reference
    ``framework/version.h`` IsProgramVersionSupported +
    ``op_compatible_info.cc``). ``check=False`` skips the gate (tooling
    that only inspects the graph)."""
    p = pb.ProgramDesc()
    p.ParseFromString(data)
    blocks = []
    for b in p.blocks:
        blocks.append(
            {
                "idx": b.idx,
                "parent_idx": b.parent_idx,
                "vars": [
                    {
                        "name": v.name,
                        "shape": list(v.shape),
                        "dtype": v.dtype,
                        "persistable": v.persistable,
                        "stop_gradient": v.stop_gradient,
                        "is_data": v.is_data,
                        "is_parameter": v.is_parameter,
                        "trainable": v.trainable,
                    }
                    for v in b.vars
                ],
                "ops": [
                    {
                        "type": o.type,
                        "inputs": {s.slot: list(s.args) for s in o.inputs},
                        "outputs": {s.slot: list(s.args) for s in o.outputs},
                        "attrs": {k: _attr_from_pb(a) for k, a in o.attrs.items()},
                    }
                    for o in b.ops
                ],
            }
        )
    desc = {
        "version": p.version,
        "random_seed": p.random_seed,
        "blocks": blocks,
        "param_grad_map": dict(p.param_grad_map),
        "feed_names": list(p.feed_names),
        "fetch_names": list(p.fetch_names),
    }
    if check:
        from ..compat import CompatibleInfo, check_program_compatible

        info = check_program_compatible(desc)
        if not info:
            cls = (ProgramVersionError
                   if info.status == CompatibleInfo.UNSUPPORTED_VERSION
                   else ProgramCompatError)
            raise cls("program is not loadable by this build: %r"
                      % (info,), status=info.status)
    return desc
