"""Combined tensor-file serde ("PTC1" format) — Python surface of
``native/tensor_io.cc`` (the reference's save_combine/load_combine ops,
``operators/save_combine_op.cc``). The native library does the file IO
when a toolchain exists; the struct-based fallback writes byte-identical
files, so the two interchange."""

import os
import struct

import numpy as np

__all__ = ["save_combine", "load_combine"]

_CODE_OF = {"float32": 0, "float64": 1, "int32": 2, "int64": 3, "uint8": 4,
            "bfloat16": 5, "float16": 6, "bool": 7, "int8": 8, "int16": 9,
            "uint16": 10, "uint32": 11, "uint64": 12}
_NP_OF = {}


def _np_dtype(code):
    global _NP_OF
    if not _NP_OF:
        _NP_OF = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64,
                  4: np.uint8, 6: np.float16, 7: np.bool_, 8: np.int8,
                  9: np.int16, 10: np.uint16, 11: np.uint32, 12: np.uint64}
        try:
            import ml_dtypes

            _NP_OF[5] = ml_dtypes.bfloat16
        except ImportError:
            pass
    if code not in _NP_OF:
        raise ValueError("unsupported dtype code %d" % code)
    return np.dtype(_NP_OF[code])


def _code(arr):
    name = arr.dtype.name
    if name not in _CODE_OF:
        raise ValueError("unsupported dtype %s" % name)
    return _CODE_OF[name]


def _native():
    from ... import native

    return native.load_tensor_io()  # memoized by the native package


def save_combine(path, arrays, atomic=True):
    """Write named arrays (dict or (name, array) iterable) to one file.
    Format limit: ndim <= 16 (enforced symmetrically at save time).

    ``atomic=True`` (default): the bytes land in ``<path>.tmp-<pid>``,
    are fsync'd, and only then renamed over ``path`` — a crash at ANY
    instant leaves either the old intact file or the new intact file,
    never a torn one (the reference's save ops write in place, so a
    killed worker could leave a half-checkpoint that load half-applies).
    ``atomic=False`` restores the in-place write for callers that own
    their own staging (the tmp-dir checkpoint writer)."""
    items = list(arrays.items()) if isinstance(arrays, dict) else list(arrays)
    items = [(n, np.ascontiguousarray(a)) for n, a in items]
    for n, a in items:
        if a.ndim > 16:
            raise ValueError("PTC1 stores at most 16 dims; %r has %d"
                             % (n, a.ndim))
    lib = _native()
    if not atomic:
        if lib is not None:
            _save_native(lib, path, items)
        else:
            _save_py(path, items)
        return
    tmp = "%s.tmp-%d" % (path, os.getpid())
    try:
        if lib is not None:
            _save_native(lib, tmp, items)
            _fsync_path(tmp)
        else:
            _save_py(tmp, items)
        from .. import faults as _faults

        _faults.check("io.write")  # simulated crash: tmp written, dest untouched
        os.replace(tmp, path)
    except BaseException:  # crash-consistency: never leave tmp behind on a surfaced error
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _save_native(lib, path, items):
    import ctypes

    h = lib.tio_open_write(path.encode())
    if not h:
        raise IOError("cannot open %s for writing" % path)
    try:
        for name, a in items:
            dims = (ctypes.c_int64 * max(a.ndim, 1))(*(a.shape or (0,)))
            rc = lib.tio_write_tensor(
                h, name.encode(), _code(a), a.ndim, dims,
                a.ctypes.data_as(ctypes.c_void_p), a.nbytes)
            if rc != 0:
                raise IOError("tio_write_tensor(%s) rc=%d" % (name, rc))
    finally:
        if lib.tio_close_write(h) != 0:
            raise IOError("tio_close_write failed for %s" % path)


def _save_py(path, items):
    with open(path, "wb") as f:
        f.write(b"PTC1")
        f.write(struct.pack("<I", len(items)))
        for name, a in items:
            nb = name.encode()
            f.write(struct.pack("<I", len(nb)))
            f.write(nb)
            f.write(struct.pack("<II", _code(a), a.ndim))
            for d in a.shape:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<Q", a.nbytes))
            f.write(a.tobytes())
        f.flush()
        os.fsync(f.fileno())


def load_combine(path):
    """Read a PTC1 file -> dict name -> np.ndarray (insertion-ordered)."""
    lib = _native()
    return (_load_native(lib, path) if lib is not None else _load_py(path))


def _load_native(lib, path):
    import ctypes

    h = lib.tio_open_read(path.encode())
    if not h:
        raise IOError("cannot read %s (missing or corrupt)" % path)
    try:
        out = {}
        name_buf = ctypes.create_string_buffer(4096)
        dims = (ctypes.c_int64 * 16)()
        dtype_c = ctypes.c_int()
        nbytes_c = ctypes.c_int64()
        for i in range(lib.tio_count(h)):
            ndim = lib.tio_entry_meta(h, i, name_buf, 4096,
                                      ctypes.byref(dtype_c), dims,
                                      ctypes.byref(nbytes_c))
            if ndim < 0:
                raise IOError("corrupt entry %d in %s" % (i, path))
            shape = tuple(dims[d] for d in range(ndim))
            a = np.empty(shape, dtype=_np_dtype(dtype_c.value))
            if a.nbytes != nbytes_c.value:
                raise IOError("size mismatch for entry %d in %s" % (i, path))
            rc = lib.tio_read_data(h, i, a.ctypes.data_as(ctypes.c_void_p),
                                   a.nbytes)
            if rc != 0:
                raise IOError("tio_read_data rc=%d for %s" % (rc, path))
            out[name_buf.value.decode()] = a
        return out
    finally:
        lib.tio_close_read(h)


def _load_py(path):
    with open(path, "rb") as f:
        if f.read(4) != b"PTC1":
            raise IOError("%s is not a PTC1 file" % path)
        (count,) = struct.unpack("<I", f.read(4))
        out = {}
        for _ in range(count):
            (name_len,) = struct.unpack("<I", f.read(4))
            name = f.read(name_len).decode()
            code, ndim = struct.unpack("<II", f.read(8))
            shape = tuple(struct.unpack("<Q", f.read(8))[0]
                          for _ in range(ndim))
            (nbytes,) = struct.unpack("<Q", f.read(8))
            dt = _np_dtype(code)
            a = np.frombuffer(f.read(nbytes), dtype=dt).reshape(shape).copy()
            out[name] = a
        return out
