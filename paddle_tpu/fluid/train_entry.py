"""C-embedder TRAINING entry (reference
``paddle/fluid/train/demo/demo_trainer.cc:1`` proves C++-only training;
here the compute path is XLA, so the ``trn_*`` C ABI in
``native/predictor.cc`` hosts an embedded interpreter and drives this
class): a train program saved with ``fluid.save`` (.pdmodel with
backward + optimizer ops, .pdparams, .pdopt) is reloaded and stepped
with caller-fed batches; the whole step still executes as one compiled
XLA program with donated buffers."""

import numpy as np

from . import io as fluid_io
from .executor import Executor, Scope, scope_guard
from .framework import Program

__all__ = ["CTrainer"]


class CTrainer:
    def __init__(self, model_path):
        with open(model_path + ".pdmodel", "rb") as f:
            self.program = Program.parse_from_string(f.read())
        self.scope = Scope()
        self.exe = Executor()
        with scope_guard(self.scope):
            fluid_io.load(self.program, model_path)

    def step(self, feed, fetch_name):
        """One optimizer step; returns the fetched value as a
        contiguous float32 ndarray (the C ABI's output dtype)."""
        with scope_guard(self.scope):
            (out,) = self.exe.run(self.program, feed=feed,
                                  fetch_list=[fetch_name])
        return np.ascontiguousarray(np.asarray(out), dtype=np.float32)

    def save(self, model_path):
        """Checkpoint params + optimizer state + program back out."""
        with scope_guard(self.scope):
            fluid_io.save(self.program, model_path)
