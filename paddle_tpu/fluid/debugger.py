"""Program inspection utilities (reference ``python/paddle/fluid/debugger.py``
``pprint_program_codes``/``draw_block_graphviz`` and ``net_drawer.py``).

Pure-host tooling over the Program IR: a readable text dump and a Graphviz
dot export (ops as boxes, vars as ellipses). No graphviz binary is needed —
we emit dot source; render externally if desired."""

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _fmt_attr(v):
    s = repr(v)
    return s if len(s) <= 60 else s[:57] + "..."


def pprint_block_codes(block, show_backward=False):
    """Return a readable text listing of one block's vars + ops."""
    lines = ["block[%d]:" % block.idx]
    for name in sorted(block.vars):
        var = block.vars[name]
        extra = []
        if getattr(var, "persistable", False):
            extra.append("persistable")
        if getattr(var, "stop_gradient", False):
            extra.append("stop_gradient")
        lines.append("  var %s : shape=%s dtype=%s %s"
                     % (name, getattr(var, "shape", None),
                        getattr(var, "dtype", None), " ".join(extra)))
    for i, op in enumerate(block.ops):
        if not show_backward and op.type.endswith("_grad"):
            continue
        ins = {k: v for k, v in op.inputs.items()}
        outs = {k: v for k, v in op.outputs.items()}
        attrs = ", ".join("%s=%s" % (k, _fmt_attr(v))
                          for k, v in sorted(op.attrs.items()))
        lines.append("  op[%d] %s(%s) -> %s {%s}" % (i, op.type, ins, outs,
                                                     attrs))
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False):
    """Text dump of every block in the program."""
    return "\n".join(pprint_block_codes(b, show_backward)
                     for b in program.blocks)


def draw_block_graphviz(block, highlights=None, path=None):
    """Emit Graphviz dot for one block: op nodes (boxes) wired through var
    nodes (ellipses). ``highlights`` is an optional set of var names drawn
    in red. Writes to ``path`` if given; returns the dot source."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}

    def var_node(name):
        if name not in var_ids:
            var_ids[name] = "var_%d" % len(var_ids)
            color = ', color=red, fontcolor=red' if name in highlights else ""
            lines.append('  %s [label="%s", shape=ellipse%s];'
                         % (var_ids[name], name, color))
        return var_ids[name]

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        lines.append('  %s [label="%s", shape=box, style=filled, '
                     'fillcolor=lightgrey];' % (op_id, op.type))
        for names in op.inputs.values():
            for n in names:
                lines.append("  %s -> %s;" % (var_node(n), op_id))
        for names in op.outputs.values():
            for n in names:
                lines.append("  %s -> %s;" % (op_id, var_node(n)))
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
