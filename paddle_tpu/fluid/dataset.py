"""Dataset engine — reference ``python/paddle/fluid/dataset.py`` +
C++ ``framework/data_set.h:135`` / ``data_feed.cc`` (MultiSlotDataFeed).

The reference streams multi-slot text files through C++ channels into
per-thread DeviceWorkers. TPU-native redesign: files parse on the host
(native C++ line parser, ``native/data_feed.cc``, with a numpy fallback),
samples shuffle in host memory, and batches assemble into the executor's
feed dicts — dense slots stack to ``[N, d]``, ragged slots flatten to the
bounded-LoD encoding (``fluid/lod.py``) so every device shape stays
static. ``Executor.train_from_dataset`` drives one pass end-to-end.

Line format (reference MultiSlotDataFeed): per slot ``<num> <v>*num``;
'u' (int64 feasign) slots come from int64 use_vars, 'f' slots otherwise.
"""

import os
import subprocess
import threading

import numpy as np

from . import lod as _lod
from .framework import Variable, convert_dtype

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset", "FileInstantDataset", "BoxPSDataset"]


class DatasetFactory:
    """Reference ``dataset.py:22``: name -> dataset instance."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        kinds = {"InMemoryDataset": InMemoryDataset,
                 "QueueDataset": QueueDataset,
                 "FileInstantDataset": FileInstantDataset,
                 "BoxPSDataset": BoxPSDataset}
        if datafeed_class not in kinds:
            raise ValueError("unknown dataset class %r (one of %s)"
                             % (datafeed_class, sorted(kinds)))
        return kinds[datafeed_class]()


def _numpy_parse(text, types):
    """Fallback multislot parser: returns per-slot (values, offsets)."""
    n_slots = len(types)
    vals = [[] for _ in range(n_slots)]
    offs = [[0] for _ in range(n_slots)]
    for ln, line in enumerate(text.splitlines()):
        tok = line.split()
        if not tok:
            continue
        i = 0
        for s in range(n_slots):
            if i >= len(tok):
                raise ValueError("line %d: missing slot %d" % (ln, s))
            num = int(tok[i])
            i += 1
            if num <= 0:
                raise ValueError("line %d: slot %d has num=%d" % (ln, s,
                                                                  num))
            seg = tok[i:i + num]
            if len(seg) != num:
                raise ValueError("line %d: slot %d truncated" % (ln, s))
            conv = int if types[s] == "u" else float
            vals[s].extend(conv(t) for t in seg)
            offs[s].append(offs[s][-1] + num)
            i += num
    out = []
    for s in range(n_slots):
        dt = np.int64 if types[s] == "u" else np.float32
        out.append((np.asarray(vals[s], dt),
                    np.asarray(offs[s], np.int64)))
    return out


def _native_parse(lib, data, types):
    import ctypes

    n_slots = len(types)
    i64 = ctypes.c_int64
    counts = (i64 * n_slots)()
    n_lines = lib.dfd_count(data, len(data), n_slots, counts)
    if n_lines < 0:
        raise ValueError("malformed multislot line %d" % (-n_lines - 1))
    fbufs, ubufs, obufs = [], [], []
    fptrs = (ctypes.POINTER(ctypes.c_float) * n_slots)()
    uptrs = (ctypes.POINTER(i64) * n_slots)()
    optrs = (ctypes.POINTER(i64) * n_slots)()
    for s in range(n_slots):
        fa = np.zeros(counts[s] if types[s] == "f" else 0, np.float32)
        ua = np.zeros(counts[s] if types[s] == "u" else 0, np.int64)
        oa = np.zeros(n_lines + 1, np.int64)
        fbufs.append(fa)
        ubufs.append(ua)
        obufs.append(oa)
        fptrs[s] = fa.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        uptrs[s] = ua.ctypes.data_as(ctypes.POINTER(i64))
        optrs[s] = oa.ctypes.data_as(ctypes.POINTER(i64))
    rc = lib.dfd_parse(data, len(data), n_slots,
                       "".join(types).encode(), fptrs, uptrs, optrs)
    if rc != 0:
        raise ValueError("multislot parse failed")
    return [(fbufs[s] if types[s] == "f" else ubufs[s], obufs[s])
            for s in range(n_slots)]


class DatasetBase:
    """Reference ``dataset.py:64``: config (vars/files/batch) + parsing."""

    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist = []
        self._use_vars = []
        self._pipe_command = None
        self._hdfs_config = None
        self._parse_lib = None
        self._parse_lib_tried = False
        self._rng = np.random.RandomState(0)

    # -- config (reference-shaped setters) ---------------------------------
    def set_pipe_command(self, pipe_command):
        """Shell filter each file streams through before parsing (the
        reference pipes every file through this command)."""
        self._pipe_command = pipe_command

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        for v in var_list:
            if not isinstance(v, Variable):
                raise TypeError("set_use_var takes Variables, got %r" % v)
        self._use_vars = list(var_list)

    def set_hdfs_config(self, fs_name, fs_ugi):
        self._hdfs_config = (fs_name, fs_ugi)

    def set_seed(self, seed):
        self._rng = np.random.RandomState(seed)

    # -- parsing ------------------------------------------------------------
    def _slot_types(self):
        types = []
        for v in self._use_vars:
            dt = convert_dtype(v.dtype or "float32")
            types.append("u" if np.issubdtype(np.dtype(dt), np.integer)
                         else "f")
        return types

    def _read_file(self, fname):
        if self._hdfs_config is not None and fname.startswith("hdfs:"):
            from ..fs import HDFSClient

            client = HDFSClient(self._hdfs_config[0], self._hdfs_config[1])
            raw = client.cat(fname)
        else:
            with open(fname, "rb") as f:
                raw = f.read()
        if self._pipe_command:
            raw = subprocess.run(self._pipe_command, shell=True, input=raw,
                                 capture_output=True, check=True).stdout
        return raw

    def _parse_file(self, fname):
        """-> list over samples; each sample is a tuple of per-slot 1-D
        numpy arrays."""
        if not self._use_vars:
            raise RuntimeError("set_use_var must be called before loading")
        types = self._slot_types()
        raw = self._read_file(fname)
        if not self._parse_lib_tried:
            from .. import native

            self._parse_lib = native.load_data_feed()
            self._parse_lib_tried = True
        if self._parse_lib is not None:
            slots = _native_parse(self._parse_lib, raw, types)
        else:
            slots = _numpy_parse(raw.decode(), types)
        n_lines = len(slots[0][1]) - 1
        samples = []
        for i in range(n_lines):
            samples.append(tuple(
                vals[offs[i]:offs[i + 1]] for vals, offs in slots))
        return samples

    # -- batching ------------------------------------------------------------
    @staticmethod
    def _lod_bound(n):
        """Static physical bound for a ragged batch's flat rows: next
        power of two (min 16). Without this every distinct token total
        would be a fresh feed signature -> a fresh XLA compile per batch;
        bucketing collapses the signatures to O(log max_len)."""
        b = 16
        while b < n:
            b *= 2
        return b

    def _batch_to_feed(self, batch):
        """samples -> executor feed dict honoring each use_var's shape:
        ragged (lod_level>0) slots go bounded-LoD (zero-padded to a
        power-of-two row bound), dense slots stack."""
        feed = {}
        for si, var in enumerate(self._use_vars):
            cols = [s[si] for s in batch]
            if getattr(var, "lod_level", 0) and var.lod_level > 0:
                flat = np.concatenate(cols)
                if flat.ndim == 1:
                    flat = flat[:, None]
                bound = self._lod_bound(flat.shape[0])
                if bound > flat.shape[0]:
                    pad = np.zeros((bound - flat.shape[0],) + flat.shape[1:],
                                   flat.dtype)
                    flat = np.concatenate([flat, pad])
                feed[var.name] = _lod.LoDTensor(
                    flat, [[len(c) for c in cols]])
            else:
                arrs = [np.asarray(c) for c in cols]
                shape = [d for d in (var.shape or []) if d not in (-1,
                                                                   None)]
                if shape:
                    arrs = [a.reshape(shape) for a in arrs]
                feed[var.name] = np.stack(arrs)
        return feed

    def _iter_batches(self, samples, drop_last=False):
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self._batch_size:
                yield self._batch_to_feed(buf)
                buf = []
        if buf and not drop_last:
            yield self._batch_to_feed(buf)

    def batch_reader(self, drop_last=False):
        raise NotImplementedError

    def desc(self):
        return {"batch_size": self._batch_size, "thread": self._thread_num,
                "files": list(self._filelist),
                "slots": [v.name for v in self._use_vars],
                "types": self._slot_types() if self._use_vars else []}


class InMemoryDataset(DatasetBase):
    """Reference ``dataset.py:276``: load all files to host memory, then
    shuffle locally or across trainers."""

    def __init__(self):
        super().__init__()
        self._samples = []
        self._preload_threads = None

    def load_into_memory(self):
        if self._thread_num <= 1 or len(self._filelist) <= 1:
            self._samples = [s for f in self._filelist
                             for s in self._parse_file(f)]
            return
        results = [None] * len(self._filelist)
        errors = []

        def work(idx, fname):
            try:
                results[idx] = self._parse_file(fname)
            except Exception as e:  # surfaced below with the filename
                errors.append((fname, e))

        threads = []
        for i, f in enumerate(self._filelist):
            t = threading.Thread(target=work, args=(i, f))
            t.start()
            threads.append(t)
            if len(threads) >= self._thread_num:
                threads.pop(0).join()
        for t in threads:
            t.join()
        if errors:
            fname, err = errors[0]
            raise RuntimeError("failed to parse %r: %s" % (fname, err)) \
                from err
        self._samples = [s for r in results for s in r]

    def preload_into_memory(self, thread_num=None):
        if thread_num:
            self.set_thread(thread_num)
        t = threading.Thread(target=self.load_into_memory)
        t.start()
        self._preload_threads = [t]

    def wait_preload_done(self):
        for t in self._preload_threads or []:
            t.join()
        self._preload_threads = None

    def local_shuffle(self):
        self._rng.shuffle(self._samples)

    def set_exchange(self, server, endpoints, seed=None):
        """Enable the network sample exchange for global_shuffle:
        ``server`` is this trainer's ``ExchangeServer``
        (distributed/sample_exchange.py), ``endpoints`` every trainer's
        exchange endpoint. With this set, each trainer loads only its
        own file shard and global_shuffle exchanges samples — O(data/N)
        host memory (reference GlobalShuffle, data_set.h:100)."""
        self._exchange = (server, list(endpoints), seed)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Distributed shuffle. With ``set_exchange`` configured: the
        reference's exchange semantics — samples hash-route between
        trainers over TCP, every trainer keeps a random disjoint ~1/N of
        the global data while having loaded only its own files.
        Without it (``fleet`` only): DEGRADED mode — every trainer must
        have loaded the FULL filelist; a positional hash keeps 1/N and
        discards the rest (correct result, O(global-data) memory)."""
        self._rng.shuffle(self._samples)
        exchange = getattr(self, "_exchange", None)
        if exchange is not None:
            from ..distributed.sample_exchange import exchange_shuffle

            server, endpoints, seed = exchange
            if seed is None:
                seed = int(self._rng.randint(0, 2 ** 31 - 1))
            self._samples = exchange_shuffle(self._samples, server,
                                             endpoints, seed=seed)
            return
        if fleet is None:
            return
        trainer_id = fleet.worker_index()
        n = max(1, fleet.worker_num())
        self._samples = [s for i, s in enumerate(self._samples)
                         if i % n == trainer_id]

    def release_memory(self):
        self._samples = []

    def get_memory_data_size(self, fleet=None):
        n = len(self._samples)
        return n * fleet.worker_num() if fleet is not None else n

    def get_shuffle_data_size(self, fleet=None):
        return len(self._samples)

    def batch_reader(self, drop_last=False):
        def reader():
            for feed in self._iter_batches(self._samples, drop_last):
                yield feed

        return reader


class QueueDataset(DatasetBase):
    """Reference ``dataset.py:646``: streaming — files parse on a
    background thread and batches queue ahead of the consumer; nothing is
    retained."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for local_shuffle "
            "(reference raises the same)")

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for global_shuffle")

    def batch_reader(self, drop_last=False):
        """Producer thread parses files into batches; batches stream
        through the native bounded channel (``native/channel.cc``, the
        reference's ``framework/channel.h`` conduit) when the toolchain
        is present, else a Python queue."""
        # In-process handoff via queue.Queue passes object references; the
        # native channel pays pickle+copy per batch, which only wins when
        # consumers live outside the interpreter (or to exercise the native
        # conduit) — so it is opt-in.
        if os.environ.get("PADDLE_TPU_NATIVE_CHANNEL") == "1":
            from .. import native

            if native.load_channel() is not None:
                return self._reader_over_channel(drop_last)
        return self._reader_over_queue(drop_last)

    def _produce_batches(self, drop_last):
        buf = []
        for f in self._filelist:
            for s in self._parse_file(f):
                buf.append(s)
                if len(buf) == self._batch_size:
                    yield self._batch_to_feed(buf)
                    buf = []
        if buf and not drop_last:
            yield self._batch_to_feed(buf)

    def _reader_over_channel(self, drop_last):
        import pickle

        def reader():
            # fresh channel per pass: the reader is re-invoked every epoch
            from .. import native

            chan = native.Channel(capacity=max(2, self._thread_num * 2))

            def produce():
                try:
                    for feed in self._produce_batches(drop_last):
                        chan.put(pickle.dumps(feed, protocol=4))
                except Exception as e:
                    try:
                        blob = pickle.dumps(("__dataset_error__", e),
                                            protocol=4)
                    except Exception:
                        # exception not picklable — surface its repr instead
                        blob = pickle.dumps(
                            ("__dataset_error__",
                             RuntimeError(repr(e))), protocol=4)
                    try:
                        chan.put(blob)
                    except Exception:
                        pass  # consumer closed early; nobody to report to
                finally:
                    chan.close()

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            try:
                while True:
                    blob = chan.get()
                    if blob is None:
                        break
                    item = pickle.loads(blob)  # trusted: bytes from OUR child worker over a private channel
                    if isinstance(item, tuple) and len(item) == 2 and \
                            item[0] == "__dataset_error__":
                        raise RuntimeError(
                            "QueueDataset stream failed") from item[1]
                    yield item
            finally:
                # wake a blocked producer, wait for it to leave the channel,
                # then free — destroying under a blocked put would be UAF
                chan.close()
                t.join(timeout=10)
                if not t.is_alive():
                    chan.destroy()

        return reader

    def _reader_over_queue(self, drop_last):
        import queue as _q

        def reader():
            q = _q.Queue(maxsize=max(2, self._thread_num * 2))
            end = object()

            def produce():
                try:
                    for feed in self._produce_batches(drop_last):
                        q.put(feed)
                    q.put(end)
                except Exception as e:  # surfaced in the consumer
                    q.put(("__dataset_error__", e))

            threading.Thread(target=produce, daemon=True).start()
            while True:
                item = q.get()
                if item is end:
                    break
                if isinstance(item, tuple) and len(item) == 2 and \
                        item[0] == "__dataset_error__":
                    raise RuntimeError(
                        "QueueDataset stream failed") from item[1]
                yield item

        return reader


class FileInstantDataset(QueueDataset):
    """Reference ``dataset.py:729``: QueueDataset flavor whose feed reads
    instances straight from the file worker — same streaming semantics
    here."""


class BoxPSDataset(InMemoryDataset):
    """Reference ``dataset.py:767``: dataset bound to an embedded parameter
    server (BoxPS) — ``begin_pass``/``end_pass`` bracket an epoch so the PS
    tier can sync its sparse tables around it.

    TPU-native analogue: the PS tier is the host-sharded embedding store
    (``paddle_tpu/distributed/ps.py``, native ``ps_store.cc``).
    ``begin_pass`` drains any async pushers registered on the global table
    registry so the epoch reads settled rows; ``end_pass`` flushes pushes
    accumulated during the pass and runs geo-communicator syncs."""

    def begin_pass(self):
        from ..distributed import ps as _ps

        for pusher in _ps.registered_pushers():
            pusher.flush()

    def end_pass(self):
        from ..distributed import ps as _ps

        for pusher in _ps.registered_pushers():
            pusher.flush()
        for comm in _ps.registered_communicators():
            comm.maybe_sync(force=True)
