"""In-graph evaluators with persistable accumulator state (reference
``python/paddle/fluid/evaluator.py``: ``Evaluator:52``, ``ChunkEvaluator:122``,
``EditDistance:195``, ``DetectionMAP:273``).

TPU-first shape: each evaluator appends its per-batch metric ops plus
accumulate ops (``state = state + batch_stat``) to the *main* program, so a
normal ``exe.run(main_program)`` advances the accumulators on device — no
host round-trip per batch. ``reset(exe)`` runs a tiny generated program that
``fill_constant``-zeros the persistable state vars through the same
scope-writeback path the optimizers use. ``DetectionMAP`` aggregates on the
host (the reference's ``detection_map`` op is a sequential CPU kernel; a
host metric is the idiomatic equivalent)."""

import numpy as np

from . import layers
from .framework import Program, program_guard

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _fetch_state(var, scope=None):
    from .executor import global_scope

    value = (scope or global_scope()).find_var(var.name)
    if value is None:
        raise RuntimeError("evaluator state %r not found in scope — run the "
                           "startup program first" % var.name)
    return float(np.asarray(value).reshape(-1)[0])


class Evaluator:
    """Base: owns persistable state vars; subclasses append update ops."""

    def __init__(self, name=None, **kwargs):
        from . import unique_name

        self.states = []
        self.metrics = []
        self.helper = None
        # unique per instance — two evaluators in one program must not
        # share accumulator vars
        self._name = name or unique_name.generate(self.__class__.__name__)

    def _create_state(self, suffix, dtype, shape):
        var = layers.create_global_var(
            shape=list(shape), value=0.0, dtype=dtype, persistable=True,
            name="%s_%s" % (self._name, suffix))
        self.states.append(var)
        return var

    def reset(self, executor, reset_program=None):
        """Zero every state var (reference ``evaluator.py:84``)."""
        if reset_program is None:
            reset_program = Program()
        with program_guard(reset_program):
            blk = reset_program.global_block()
            for state in self.states:
                v = blk.create_var(name=state.name, shape=state.shape,
                                   dtype=state.dtype, persistable=True)
                layers.fill_constant(shape=list(state.shape),
                                     dtype=state.dtype, value=0.0, out=v)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Accumulate chunk counts across batches; report precision/recall/F1
    (reference ``evaluator.py:122``; counts from the ``chunk_eval`` op)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, name=None):
        super().__init__(name=name)
        (precision, recall, f1, num_infer, num_label,
         num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self.num_infer_chunks = self._create_state("num_infer", "int64", [1])
        self.num_label_chunks = self._create_state("num_label", "int64", [1])
        self.num_correct_chunks = self._create_state("num_correct", "int64",
                                                     [1])
        for state, batch in ((self.num_infer_chunks, num_infer),
                             (self.num_label_chunks, num_label),
                             (self.num_correct_chunks, num_correct)):
            acc = layers.elementwise_add(state, layers.cast(batch, "int64"))
            layers.assign(acc, output=state)
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None, scope=None):
        infer, label, correct = (_fetch_state(s, scope)
                                 for s in self.states)
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return precision, recall, f1


class EditDistance(Evaluator):
    """Average edit distance + instance error rate across batches
    (reference ``evaluator.py:195``)."""

    def __init__(self, input, label, ignored_tokens=None, name=None):
        super().__init__(name=name)
        distances, seq_num = layers.edit_distance(
            input=input, label=label, normalized=False,
            ignored_tokens=ignored_tokens)
        self.total_distance = self._create_state("total_distance",
                                                 "float32", [1])
        self.seq_num = self._create_state("seq_num", "int64", [1])
        self.instance_error = self._create_state("instance_error",
                                                 "int64", [1])
        batch_dist = layers.reduce_sum(distances)
        batch_err = layers.reduce_sum(
            layers.cast(layers.greater_than(
                distances, layers.fill_constant([1], "float32", 0.0)),
                "int64"))
        for state, batch in ((self.total_distance, batch_dist),
                             (self.seq_num, seq_num),
                             (self.instance_error, batch_err)):
            acc = layers.elementwise_add(
                state, batch if batch.dtype == state.dtype
                else layers.cast(batch, state.dtype))
            layers.assign(acc, output=state)
        self.metrics.extend([distances, seq_num])

    def eval(self, executor, eval_program=None, scope=None):
        total = _fetch_state(self.total_distance, scope)
        n = _fetch_state(self.seq_num, scope)
        err = _fetch_state(self.instance_error, scope)
        avg_distance = total / n if n else 0.0
        avg_instance_error = err / n if n else 0.0
        return avg_distance, avg_instance_error


class DetectionMAP:
    """Mean average precision over accumulated detections (capability of
    reference ``evaluator.py:273`` / ``detection_map_op.cc``, evaluated on
    the host: VOC 11-point or integral AP).

    ``update(detections, gt_boxes, gt_labels, difficult=None)`` per image:
    ``detections`` is ``[M, 6]`` rows ``(label, score, x1, y1, x2, y2)``;
    ``gt_boxes`` ``[G, 4]``; ``gt_labels`` ``[G]``.
    """

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.class_num = int(class_num)
        self.overlap_threshold = float(overlap_threshold)
        self.evaluate_difficult = bool(evaluate_difficult)
        self.ap_version = ap_version
        self.reset()

    def reset(self, *_args):
        self._dets = [[] for _ in range(self.class_num)]  # (score, tp)
        self._npos = np.zeros(self.class_num, np.int64)

    @staticmethod
    def _iou(box, boxes):
        x1 = np.maximum(box[0], boxes[:, 0])
        y1 = np.maximum(box[1], boxes[:, 1])
        x2 = np.minimum(box[2], boxes[:, 2])
        y2 = np.minimum(box[3], boxes[:, 3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        a = (box[2] - box[0]) * (box[3] - box[1])
        b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        union = a + b - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)

    def update(self, detections, gt_boxes, gt_labels, difficult=None):
        detections = np.asarray(detections, np.float64).reshape(-1, 6)
        gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        gt_labels = np.asarray(gt_labels, np.int64).reshape(-1)
        difficult = (np.zeros_like(gt_labels, bool) if difficult is None
                     else np.asarray(difficult, bool).reshape(-1))
        for c in range(self.class_num):
            mask = gt_labels == c
            if self.evaluate_difficult:
                self._npos[c] += int(mask.sum())
            else:
                self._npos[c] += int((mask & ~difficult).sum())
        matched = np.zeros(len(gt_boxes), bool)
        order = np.argsort(-detections[:, 1])
        for i in order:
            label, score = int(detections[i, 0]), detections[i, 1]
            if not 0 <= label < self.class_num:
                continue
            cand = np.where(gt_labels == label)[0]
            tp = 0
            if len(cand):
                ious = self._iou(detections[i, 2:6], gt_boxes[cand])
                j = int(np.argmax(ious))
                if ious[j] >= self.overlap_threshold:
                    g = cand[j]
                    if not self.evaluate_difficult and difficult[g]:
                        continue  # neither TP nor FP
                    if not matched[g]:
                        matched[g] = True
                        tp = 1
            self._dets[label].append((score, tp))

    def _ap(self, recalls, precisions):
        if self.ap_version == "11point":
            return float(np.mean([
                precisions[recalls >= t].max() if (recalls >= t).any() else 0.0
                for t in np.linspace(0, 1, 11)]))
        # integral: sum precision deltas over recall steps
        order = np.argsort(recalls)
        r, p = recalls[order], precisions[order]
        prev_r, ap = 0.0, 0.0
        for ri, pi in zip(r, p):
            ap += (ri - prev_r) * pi
            prev_r = ri
        return float(ap)

    def eval(self, *_args):
        aps = []
        for c in range(self.class_num):
            if self._npos[c] == 0:
                continue  # VOC: classes with no ground truth don't count
            if not self._dets[c]:
                aps.append(0.0)
                continue
            arr = np.asarray(sorted(self._dets[c], key=lambda t: -t[0]))
            tps = np.cumsum(arr[:, 1])
            fps = np.cumsum(1 - arr[:, 1])
            recalls = tps / max(int(self._npos[c]), 1)
            precisions = tps / np.maximum(tps + fps, 1e-12)
            aps.append(self._ap(recalls, precisions))
        return float(np.mean(aps)) if aps else 0.0
