"""Global flags — reference gflags surface (``fluid.set_flags`` /
``get_flags``, ``platform/flags.cc``). Flags either map to real behavior
here (listed below) or are accepted-and-recorded for API compatibility
(reference flags that tune CUDA allocators etc. have no TPU meaning —
XLA owns memory).

Live flags:
  FLAGS_check_nan_inf      executor checks every fetched value and every
                           persistable update for non-finite numbers and
                           raises naming the program (reference
                           ``framework/details/nan_inf_utils_detail``)
  FLAGS_check_program      executor validates each program's
                           well-formedness (the program_check pass — the
                           reference's ``multi_devices_check_pass``)
                           before first compiling it
  FLAGS_cudnn_deterministic  accepted (XLA is deterministic by default)
  FLAGS_eager_delete_tensor_gb  accepted (XLA buffer lifetime)
  FLAGS_anomaly_policy     what a non-finite training step does:
                           "raise" (default, legacy FloatingPointError),
                           "skip_step" (discard the update, keep going),
                           "rollback" (restore the last checkpoint —
                           needs Executor.run(checkpoint=...)). Env:
                           PADDLE_ANOMALY_POLICY.
  FLAGS_anomaly_skip_budget  consecutive anomalous steps skip_step /
                           rollback tolerate before raising anyway
                           (default 3). Env: PADDLE_ANOMALY_SKIP_BUDGET.
"""

import os

__all__ = ["set_flags", "get_flags"]

_FLAGS = {
    "FLAGS_check_nan_inf": os.environ.get("FLAGS_check_nan_inf",
                                          "0") in ("1", "true", "True"),
    "FLAGS_check_program": os.environ.get("FLAGS_check_program",
                                          "0") in ("1", "true", "True"),
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_anomaly_policy": os.environ.get("PADDLE_ANOMALY_POLICY",
                                           "raise"),
    "FLAGS_anomaly_skip_budget": int(
        os.environ.get("PADDLE_ANOMALY_SKIP_BUDGET", "3")),
}

_ANOMALY_POLICIES = ("raise", "skip_step", "rollback")


def set_flags(flags):
    """Set one or more global flags (dict of name -> value)."""
    for name, value in flags.items():
        _FLAGS[name] = value


def get_flags(names):
    """Read flags by name (str or list of str)."""
    if isinstance(names, str):
        return {names: _FLAGS.get(names)}
    return {n: _FLAGS.get(n) for n in names}


def check_nan_inf_enabled():
    return bool(_FLAGS.get("FLAGS_check_nan_inf"))


def check_program_enabled():
    return bool(_FLAGS.get("FLAGS_check_program"))


def anomaly_policy():
    """Validated FLAGS_anomaly_policy value (raise|skip_step|rollback).
    Validation happens at READ time so a bad env var / set_flags value
    fails the first run loudly rather than silently acting as raise."""
    p = _FLAGS.get("FLAGS_anomaly_policy", "raise")
    if p not in _ANOMALY_POLICIES:
        raise ValueError(
            "FLAGS_anomaly_policy must be one of %s, got %r"
            % ("|".join(_ANOMALY_POLICIES), p))
    return p


def anomaly_skip_budget():
    b = int(_FLAGS.get("FLAGS_anomaly_skip_budget", 3))
    if b < 0:
        raise ValueError(
            "FLAGS_anomaly_skip_budget must be >= 0, got %d" % b)
    return b
