"""Static shape/dtype inference by abstract evaluation of op lowering rules.

TPU-first replacement for the reference's per-op ``InferShape`` methods
(``shape_inference.h``): the lowering rule IS the shape function — we run it
under ``jax.eval_shape`` (no FLOPs, no memory) and read off output avals.
Unknown (batch) dims are encoded as -1 in the IR; they are substituted with a
distinctive dummy extent for abstract eval and mapped back afterwards.
"""

import numpy as np

_DUMMY = 1097  # unlikely to appear as a real static dim


def infer_op_shapes(op):
    import jax

    from .registry import LowerCtx, registry

    block = op.block
    if not registry.has(op.type):
        return
    names = []
    vals = []
    had_dummy = False
    for name in op.input_arg_names():
        v = block._find_var_recursive(name)
        if v is None:
            return
        shape = []
        for s in v.shape:
            if s == -1:
                shape.append(_DUMMY)
                had_dummy = True
            else:
                shape.append(int(s))
        names.append(name)
        vals.append(jax.ShapeDtypeStruct(tuple(shape), v.dtype))

    out_names = op.output_arg_names()

    def fn(env_vals, key):
        env = dict(zip(names, env_vals))
        ctx = LowerCtx(block, env, key)
        registry.get(op.type).lower(ctx, op)
        return {n: env[n] for n in out_names if n in env}

    outs = jax.eval_shape(fn, vals, jax.ShapeDtypeStruct((2,), np.uint32))
    for n, aval in outs.items():
        v = block._find_var_recursive(n)
        if v is None:
            continue
        shape = tuple(
            -1 if (had_dummy and s % _DUMMY == 0 and s > 0) else int(s)
            for s in aval.shape
        )
        v.shape = shape
        v.dtype = np.dtype(aval.dtype)
