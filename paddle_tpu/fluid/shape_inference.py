"""Static shape/dtype inference by abstract evaluation of op lowering rules.

TPU-first replacement for the reference's per-op ``InferShape`` methods
(``shape_inference.h``): the lowering rule IS the shape function — we run it
under ``jax.eval_shape`` (no FLOPs, no memory) and read off output avals.
Unknown (batch) dims are encoded as -1 in the IR; they are substituted with a
distinctive dummy extent for abstract eval and mapped back afterwards.
"""

import numpy as np

_DUMMY = 1097  # unlikely to appear as a real static dim
_KEY_AVAL = None


def _root_key_aval():
    """Cached key aval for abstract eval. ALWAYS threefry: inferred
    output shapes never depend on the key impl, and resolving the real
    impl would query jax.devices() — initializing the backend during
    graph CONSTRUCTION, before jax.distributed.initialize can run
    (see dygraph/parallel.py + distributed/env.py ordering)."""
    global _KEY_AVAL
    if _KEY_AVAL is None:
        import jax

        _KEY_AVAL = jax.eval_shape(
            lambda: jax.random.key(0, impl="threefry2x32"))
    return _KEY_AVAL


def infer_op_shapes(op):
    import jax

    from .registry import LowerCtx, registry

    block = op.block
    if not registry.has(op.type):
        return
    names = []
    vals = []
    had_dummy = False
    for name in op.input_arg_names():
        v = block._find_var_recursive(name)
        if v is None:
            return
        shape = []
        for s in v.shape:
            if s == -1:
                shape.append(_DUMMY)
                had_dummy = True
            else:
                shape.append(int(s))
        names.append(name)
        vals.append(jax.ShapeDtypeStruct(tuple(shape), v.dtype))

    out_names = op.output_arg_names()

    def fn(env_vals, key):
        env = dict(zip(names, env_vals))
        ctx = LowerCtx(block, env, key)
        registry.get(op.type).lower(ctx, op)
        return {n: env[n] for n in out_names if n in env}

    # a key of the ACTIVE impl (threefry [2]x uint32, rbg [4]x —
    # hardcoding one shape breaks the other); built once and cached
    outs = jax.eval_shape(fn, vals, _root_key_aval())
    for n, aval in outs.items():
        v = block._find_var_recursive(n)
        if v is None:
            continue
        shape = tuple(
            -1 if (had_dummy and s % _DUMMY == 0 and s > 0) else int(s)
            for s in aval.shape
        )
        v.shape = shape
        v.dtype = np.dtype(aval.dtype)
