"""Program-rewrite pass infrastructure (reference ``framework/ir/pass.{h,cc}``
+ ``pass_builder`` + 45 ``REGISTER_PASS`` sites, and the Python ``IrGraph``
at ``framework.py:3125``).

TPU-first stance: XLA owns fusion/layout/memory passes, so the pass layer
here only hosts *Paddle-semantic* rewrites — AMP casts, quantization,
collective insertion, pruning, visualization. Each pass is a named callable
``pass_fn(program, **kwargs) -> program`` (in-place rewrites return the same
object) registered in a global ``PassRegistry``; ``PassBuilder`` composes an
ordered pipeline the way the reference's ``BuildStrategy`` assembles its
pass list (``details/build_strategy.cc:59``)."""

__all__ = ["Pass", "PassRegistry", "PassBuilder", "register_pass",
           "apply_pass", "get_pass", "IrGraph"]


class Pass:
    """A named Program rewrite. ``fn(program, **kwargs) -> program``."""

    def __init__(self, name, fn, doc=""):
        self.name = name
        self.fn = fn
        self.__doc__ = doc or fn.__doc__

    def apply(self, program, **kwargs):
        out = self.fn(program, **kwargs)
        return program if out is None else out

    def __repr__(self):
        return "Pass(%r)" % self.name


class PassRegistry:
    def __init__(self):
        self._passes = {}

    def register(self, name, fn=None, doc=""):
        if fn is None:  # decorator form
            def deco(f):
                self._passes[name] = Pass(name, f, doc)
                return f
            return deco
        self._passes[name] = Pass(name, fn, doc)
        return fn

    def get(self, name):
        if name not in self._passes:
            raise KeyError("no pass named %r (registered: %s)"
                           % (name, ", ".join(sorted(self._passes))))
        return self._passes[name]

    def has(self, name):
        return name in self._passes

    def names(self):
        return sorted(self._passes)


_registry = PassRegistry()
register_pass = _registry.register
get_pass = _registry.get


def apply_pass(program, name, **kwargs):
    """Look up and run one registered pass."""
    return _registry.get(name).apply(program, **kwargs)


class PassBuilder:
    """Ordered pass pipeline (reference ``pass_builder.{h,cc}``)."""

    def __init__(self, names=None):
        self._pipeline = [_registry.get(n) for n in (names or [])]

    def append_pass(self, name):
        p = _registry.get(name)
        self._pipeline.append(p)
        return p

    def insert_pass(self, idx, name):
        p = _registry.get(name)
        self._pipeline.insert(idx, p)
        return p

    def remove_pass(self, idx):
        self._pipeline.pop(idx)

    def all_passes(self):
        return list(self._pipeline)

    def apply(self, program, pass_kwargs=None):
        pass_kwargs = pass_kwargs or {}
        for p in self._pipeline:
            program = p.apply(program, **pass_kwargs.get(p.name, {}))
        return program


# ---------------------------------------------------------------------------
# Built-in passes over the existing rewrites


@register_pass("amp_rewrite")
def _amp_rewrite_pass(program, amp_lists=None, dest_dtype="bfloat16"):
    """fp16/bf16 cast insertion (contrib.mixed_precision.fp16_utils)."""
    from .contrib.mixed_precision.fp16_lists import AutoMixedPrecisionLists
    from .contrib.mixed_precision.fp16_utils import rewrite_program

    rewrite_program(program, amp_lists or AutoMixedPrecisionLists(),
                    dest_dtype=dest_dtype)
    return program


@register_pass("prune")
def _prune_pass(program, targets=None):
    """Dead-op elimination toward fetch targets (Program._prune; reference
    ``framework/prune.h``)."""
    if targets is None:
        raise ValueError("prune pass needs targets=[vars or names]")
    return program._prune(targets)


@register_pass("quant_transform")
def _quant_transform_pass(program, **kwargs):
    """QAT fake-quant insertion (slim QuantizationTransformPass)."""
    from .contrib.slim.quantization.quantization_pass import (
        QuantizationTransformPass)

    QuantizationTransformPass(**kwargs).apply(program)
    return program


@register_pass("quant_freeze")
def _quant_freeze_pass(program, **kwargs):
    """Fold trained quant scales for inference (QuantizationFreezePass)."""
    from .contrib.slim.quantization.quantization_pass import (
        QuantizationFreezePass)

    QuantizationFreezePass(**kwargs).apply(program)
    return program


@register_pass("quant_int8_convert")
def _quant_int8_pass(program, weight_names=None, **kwargs):
    """Cast frozen weights to int8 storage (ConvertToInt8Pass)."""
    from .contrib.slim.quantization.quantization_pass import ConvertToInt8Pass

    ConvertToInt8Pass(**kwargs).apply(program, weight_names=weight_names)
    return program


@register_pass("collective_grad_allreduce")
def _collective_pass(program, startup_program=None, nranks=None):
    """Insert c_allreduce on every grad (transpiler.collective.GradAllReduce:
    the Fleet-collective DP rewrite)."""
    from .framework import default_startup_program
    from .transpiler.collective import GradAllReduce

    t = GradAllReduce(nranks)
    t.transpile(startup_program=startup_program or default_startup_program(),
                main_program=program)
    return program


@register_pass("local_sgd")
def _local_sgd_pass(program, startup_program=None, nranks=None, k_steps=1):
    """Periodic parameter averaging (transpiler.collective.LocalSGD)."""
    from .framework import default_startup_program
    from .transpiler.collective import LocalSGD

    t = LocalSGD(nranks, k_steps=k_steps)
    t.transpile(startup_program=startup_program or default_startup_program(),
                main_program=program)
    return program


@register_pass("graph_viz")
def _graph_viz_pass(program, path=None, block_idx=0, highlights=None):
    """Dot export (reference ``ir/graph_viz_pass.cc``)."""
    from .debugger import draw_block_graphviz

    draw_block_graphviz(program.blocks[block_idx], highlights=highlights,
                        path=path)
    return program


class IrGraph:
    """Thin graph view over a Program block (reference ``IrGraph``
    ``framework.py:3125`` wraps the C++ ``ir::Graph``). Nodes are ops and
    var names; used by slim tooling and tests to inspect structure."""

    def __init__(self, program, block_idx=0, for_test=False):
        self._program = program
        self._block = program.blocks[block_idx]
        self._for_test = for_test

    @property
    def program(self):
        return self._program

    def all_op_nodes(self):
        return list(self._block.ops)

    def all_var_names(self):
        return sorted(self._block.vars)

    def op_types(self):
        return [op.type for op in self._block.ops]

    def inputs_of(self, op):
        return [n for vs in op.inputs.values() for n in vs]

    def outputs_of(self, op):
        return [n for vs in op.outputs.values() for n in vs]

    def consumers_of(self, var_name):
        return [op for op in self._block.ops
                if var_name in self.inputs_of(op)]

    def producer_of(self, var_name):
        for op in self._block.ops:
            if var_name in self.outputs_of(op):
                return op
        return None

    def draw(self, path=None, highlights=None):
        from .debugger import draw_block_graphviz

        return draw_block_graphviz(self._block, highlights=highlights,
                                   path=path)


@register_pass("program_check")
def _program_check_pass(program, startup_program=None, feed_names=None):
    """Well-formedness validation (reference ``multi_devices_check_pass``,
    ``details/build_strategy.cc:80``): every op input must be produced by
    an earlier op (in this block or an ancestor block), fed, or
    persistable; unknown op types are reported with the op index. Raises
    ValueError with the full defect list.

    The check mirrors THIS runtime exactly: the executor materializes
    only fed values, in-block products, and persistable scope state —
    a startup program can only initialize persistable vars usefully, so
    (unlike the reference) "startup-initialized" is not a separate
    acceptance category. ``startup_program`` is accepted for signature
    parity and unused."""
    from .compat import _STRUCTURAL_OPS
    from .registry import registry as op_registry

    del startup_program  # see docstring: no extra acceptance category
    feed_names = set(feed_names or [])

    def ancestor_produced(blk):
        out = set()
        b = blk.parent_block
        while b is not None:
            for op in b.ops:
                out.update(op.output_arg_names())
            b = b.parent_block
        return out

    problems = []
    for blk in program.blocks:
        # sub-blocks (While/cond bodies) legitimately read anything their
        # ancestors produce at any point — the runtime enters them after
        # the whole parent program is lowered
        produced = ancestor_produced(blk)
        for idx, op in enumerate(blk.ops):
            if op.type == "feed":
                produced.update(op.output_arg_names())
                continue
            known = (op_registry.has(op.type)
                     or op.type in _STRUCTURAL_OPS
                     or op.type.endswith("_grad"))
            if not known:
                problems.append("block %d op[%d] %r: no lowering rule"
                                % (blk.idx, idx, op.type))
            for name in set(op.input_arg_names()):  # dedupe repeated slots
                var = blk._find_var_recursive(name)
                ok = (name in produced or name in feed_names
                      or (var is not None and
                          (getattr(var, "persistable", False)
                           or getattr(var, "is_data", False))))
                if not ok:
                    problems.append(
                        "block %d op[%d] %s: input %r is never produced, "
                        "fed, or persistable"
                        % (blk.idx, idx, op.type, name))
            produced.update(op.output_arg_names())
    if problems:
        raise ValueError("program_check found %d defect(s):\n  %s"
                         % (len(problems), "\n  ".join(problems)))
    return program
