"""LoDTensor construction helpers.

Parity: reference ``fluid/lod_tensor.py`` (``create_lod_tensor:24``,
``create_random_int_lodtensor:114``). The in-memory LoDTensor itself
lives in ``fluid/lod.py`` (bounded-LoD design); this module keeps the
reference's user-facing module path and adds the random-int builder
book models use for vocabulary-id sequences.
"""

import numpy as np

from .lod import LoDTensor, create_lod_tensor  # noqa: F401

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """LoDTensor of random ints in [low, high] with the given length-based
    LoD: first dim = sum of sequence lengths, trailing dims =
    ``base_shape`` (reference ``lod_tensor.py:114``; ``place`` is
    accepted for API compatibility — XLA owns placement here)."""
    total = int(np.sum(recursive_seq_lens[-1]))
    shape = [total] + list(base_shape)
    data = np.random.randint(low, high + 1, shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
