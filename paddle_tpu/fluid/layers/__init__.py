"""Layers namespace (reference ``python/paddle/fluid/layers/``)."""

from .. import ops as _ops  # registers all lowering rules  # noqa: F401
from . import (control_flow, detection, distributions, extras, io,
               learning_rate_scheduler, loss, metric_op,
               nn, ops, rnn, sequence_lod, tensor)
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .io import data, load
from .learning_rate_scheduler import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .metric_op import accuracy, auc
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .py_reader import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .sequence_lod import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
