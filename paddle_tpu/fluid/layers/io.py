"""Data-entry layers (reference ``layers/data.py`` / ``layers/io.py``)."""

from .. import framework
from ..layer_helper import LayerHelper

__all__ = ["data", "load"]


def load(out, file_path, load_as_fp16=None):
    """Load a tensor file into ``out`` (reference ``layers/io.py:884``
    load op). Accepts a PTC1 combined file (first/only entry) or an
    ``.npy`` written by ``save_vars``. TPU deviation: the file is read
    at program-lowering time and enters the compiled step as a
    constant — the reference's executor re-reads per run, but the op's
    canonical use is startup-program initialization, which runs once."""
    helper = LayerHelper("load", name=None)
    attrs = {"file_path": file_path}
    if load_as_fp16 is not None:
        attrs["load_as_fp16"] = bool(load_as_fp16)
    helper.append_op(type="load", inputs={}, outputs={"Out": [out]},
                     attrs=attrs)


def data(name, shape, dtype="float32", append_batch_size=True,
         lod_level=0, type=None, stop_gradient=True):
    """Declares a feed slot. ``append_batch_size`` prepends -1 like the
    reference ``fluid.layers.data``; ``fluid.data`` passes shapes verbatim."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.current_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )
