"""Data-entry layers (reference ``layers/data.py`` / ``layers/io.py``)."""

from .. import framework
from ..layer_helper import LayerHelper

__all__ = ["data"]


def data(name, shape, dtype="float32", append_batch_size=True,
         lod_level=0, type=None, stop_gradient=True):
    """Declares a feed slot. ``append_batch_size`` prepends -1 like the
    reference ``fluid.layers.data``; ``fluid.data`` passes shapes verbatim."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.current_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )
