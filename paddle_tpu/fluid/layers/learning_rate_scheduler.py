"""LR schedules as in-graph computation on the step counter.

Parity: reference ``layers/learning_rate_scheduler.py`` (8 schedules). Each
returns a Variable computed from the persistable ``@LR_STEP@`` counter, so
the schedule runs inside the compiled step — no host round-trip.
"""

import math

from ..layer_helper import LayerHelper
from . import nn, ops, tensor
from .nn import autoincreased_step_counter

__all__ = [
    "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "noam_decay", "cosine_decay",
    "linear_lr_warmup",
]


def _step_counter():
    counter = autoincreased_step_counter(counter_name="@LR_STEP@", begin=0, step=1)
    return tensor.cast(counter, "float32")


def noam_decay(d_model, warmup_steps):
    step = _step_counter()
    a = step ** -0.5
    b = step * (warmup_steps ** -1.5)
    return (d_model ** -0.5) * nn.elementwise_min(a, b)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return nn.scale(
        nn.elementwise_pow(tensor.fill_constant([1], "float32", decay_rate), div),
        scale=float(learning_rate),
    )


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return nn.scale(ops.exp(nn.scale(div, scale=-float(decay_rate))),
                    scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    denom = nn.scale(div, scale=float(decay_rate), bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant([1], "float32", float(learning_rate)), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _step_counter()
    if cycle:
        div = nn.elementwise_max(
            tensor.fill_constant([1], "float32", 1.0),
            ops.ceil(step / float(decay_steps)))
        decay_steps_var = nn.scale(div, scale=float(decay_steps))
        frac = nn.elementwise_div(step, decay_steps_var)
    else:
        step = nn.elementwise_min(
            step, tensor.fill_constant([1], "float32", float(decay_steps)))
        frac = nn.scale(step, scale=1.0 / decay_steps)
    one_minus = nn.scale(frac, scale=-1.0, bias=1.0)
    poly = nn.elementwise_pow(one_minus,
                              tensor.fill_constant([1], "float32", power))
    return nn.scale(poly, scale=float(learning_rate) - end_learning_rate,
                    bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    step = _step_counter()
    lr = tensor.fill_constant([1], "float32", values[-1])
    # evaluate from last boundary backwards via where-chains
    from .nn import elementwise_add, elementwise_mul

    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = step < float(b)
        condf = tensor.cast(cond, "float32")
        lr = elementwise_add(
            elementwise_mul(condf, tensor.fill_constant([1], "float32", v)),
            elementwise_mul(nn.scale(condf, scale=-1.0, bias=1.0), lr),
        )
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _step_counter()
    epoch = ops.floor(nn.scale(step, scale=1.0 / step_each_epoch))
    cos_arg = nn.scale(epoch, scale=math.pi / epochs)
    return nn.scale(nn.scale(ops.cos(cos_arg), bias=1.0),
                    scale=0.5 * float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _step_counter()
    if not isinstance(learning_rate, float):
        lr_after = learning_rate
    else:
        lr_after = tensor.fill_constant([1], "float32", learning_rate)
    frac = nn.scale(step, scale=1.0 / warmup_steps)
    warm = nn.scale(frac, scale=float(end_lr - start_lr), bias=float(start_lr))
    cond = step < float(warmup_steps)
    condf = tensor.cast(cond, "float32")
    return nn.elementwise_add(
        nn.elementwise_mul(condf, warm),
        nn.elementwise_mul(nn.scale(condf, scale=-1.0, bias=1.0), lr_after),
    )
