"""Tensor-manipulation layers (reference ``layers/tensor.py``)."""

import numpy as np

from .. import framework
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast", "concat",
    "sums", "assign", "fill_constant_batch_size_like", "fill_constant",
    "argmin", "argmax", "argsort", "ones", "zeros", "reverse", "has_inf",
    "has_nan", "isfinite", "range", "linspace", "zeros_like", "ones_like",
    "diag", "eye", "tensor_array_to_tensor",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", **locals())
    return helper.main_program.current_block().create_var(
        name=name, shape=(), dtype=dtype, persistable=persistable
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", **locals())
    from ..param_attr import ParamAttr

    attr = ParamAttr._to_attr(attr)
    if name:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.main_program.global_block().create_var(
        name=name, shape=shape, dtype=dtype, persistable=persistable,
        stop_gradient=True,
    )
    sb = helper.startup_program.global_block()
    sv = sb.create_var(name=var.name, shape=shape, dtype=dtype,
                       persistable=persistable)
    from ..initializer import Constant

    Constant(value)(sv, sb)
    return var


def cast(x, dtype):
    helper = LayerHelper("cast", x=x, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"out_dtype": framework.dtype_str(framework.convert_dtype(dtype))})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", **locals())
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="concat", inputs={"X": input}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign", **locals())
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(arr.dtype)
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={"shape": list(arr.shape),
                   "dtype": framework.dtype_str(arr.dtype),
                   "values": arr.ravel().tolist()},
        )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape),
               "dtype": framework.dtype_str(framework.convert_dtype(dtype)),
               "value": float(value)},
    )
    return out


def fill_constant_batch_size_like(input, shape, dtype, value, input_dim_idx=0,
                                  output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape),
               "dtype": framework.dtype_str(framework.convert_dtype(dtype)),
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx},
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="argsort", inputs={"X": [input]},
                     outputs={"Out": [out], "Indices": [ids]},
                     attrs={"axis": axis, "descending": descending})
    return out, ids


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def reverse(x, axis):
    helper = LayerHelper("reverse", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis if isinstance(axis, (list, tuple)) else [axis]})
    return out


def has_inf(x):
    helper = LayerHelper("has_inf", **locals())
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="has_inf", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_nan(x):
    helper = LayerHelper("has_nan", **locals())
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="has_nan", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite", **locals())
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="isfinite", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def range(start, end, step, dtype):
    """XLA needs a static output length, so python-scalar bounds ride as
    attrs (trace-time constants); Variable bounds are rejected at the op
    (a data-dependent length can never compile)."""
    helper = LayerHelper("range")
    inputs, attrs = {}, {"dtype": framework.dtype_str(
        framework.convert_dtype(dtype))}
    for key, val in (("Start", start), ("End", end), ("Step", step)):
        if isinstance(val, Variable):
            inputs[key] = [val]
        else:
            attrs[key.lower()] = float(val)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="range", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def linspace(start, stop, num, dtype):
    helper = LayerHelper("linspace")
    start = assign(np.asarray([start], "float32")) if not isinstance(start, Variable) else start
    stop = assign(np.asarray([stop], "float32")) if not isinstance(stop, Variable) else stop
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="linspace",
                     inputs={"Start": [start], "Stop": [stop]},
                     outputs={"Out": [out]}, attrs={"num": int(num)})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="ones_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def diag(diagonal):
    helper = LayerHelper("diag", **locals())
    if not isinstance(diagonal, Variable):  # reference accepts ndarray/list
        diagonal = assign(np.asarray(diagonal))
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op(type="diag", inputs={"Diagonal": [diagonal]},
                     outputs={"Out": [out]})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    num_columns = num_columns if num_columns is not None else num_rows
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="eye", outputs={"Out": [out]},
                     attrs={"num_rows": num_rows, "num_columns": num_columns,
                            "dtype": framework.dtype_str(framework.convert_dtype(dtype))})
    if batch_shape:
        from .nn import expand, unsqueeze

        for _ in batch_shape:
            out = unsqueeze(out, [0])
        out = expand(out, list(batch_shape) + [1, 1])
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Concat (or stack) every slot of a bounded tensor array along
    ``axis``; returns (out, out_index) like the reference
    (``layers/tensor.py:279``). Bounded semantics: all ``bound`` slots
    participate — unwritten slots are zeros — so the result matches the
    reference exactly when the array is fully written; out_index holds
    each slot's (static) size along ``axis``."""
    helper = LayerHelper("tensor_array_to_tensor", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_index = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="tensor_array_to_tensor",
        inputs={"X": [input]},
        outputs={"Out": [out], "OutIndex": [out_index]},
        attrs={"axis": int(axis), "use_stack": bool(use_stack)})
    return out, out_index
