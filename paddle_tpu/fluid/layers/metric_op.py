"""Metric layers: accuracy, auc (reference ``layers/metric_op.py``)."""

from ..layer_helper import LayerHelper

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    acc = helper.create_variable_for_type_inference("float32")
    correct = correct or helper.create_variable_for_type_inference("int32")
    total = total or helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [values], "Indices": [indices], "Label": [label]},
        outputs={"Accuracy": [acc], "Correct": [correct], "Total": [total]},
    )
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    helper = LayerHelper("auc", **locals())
    stat_pos = helper.main_program.global_block().create_var(
        name=helper.name_prefix + ".stat_pos", shape=(num_thresholds + 1,),
        dtype="float32", persistable=True, stop_gradient=True)
    stat_neg = helper.main_program.global_block().create_var(
        name=helper.name_prefix + ".stat_neg", shape=(num_thresholds + 1,),
        dtype="float32", persistable=True, stop_gradient=True)
    from ..initializer import Constant

    sb = helper.startup_program.global_block()
    for v in (stat_pos, stat_neg):
        sv = sb.create_var(name=v.name, shape=v.shape, dtype="float32",
                           persistable=True)
        Constant(0.0)(sv, sb)
    auc_out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label], "StatPos": [stat_pos],
                "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"num_thresholds": num_thresholds, "curve": curve},
    )
    return auc_out, [stat_pos, stat_neg]
