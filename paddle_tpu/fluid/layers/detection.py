"""Detection layers — reference ``python/paddle/fluid/layers/detection.py``
(27 public fns). Op semantics live in ``ops/detection_ops.py``; the
static-shape deviations from the reference's LoD outputs are documented
there (NMS/proposal outputs are fixed top-N, padded with label -1 / zero
boxes).
"""

import numpy as np

from ..layer_helper import LayerHelper
from . import nn, tensor

__all__ = [
    "prior_box", "density_prior_box", "multi_box_head", "bipartite_match",
    "target_assign", "detection_output", "ssd_loss", "rpn_target_assign",
    "retinanet_target_assign", "sigmoid_focal_loss", "anchor_generator",
    "roi_perspective_transform", "generate_proposal_labels",
    "generate_proposals", "generate_mask_labels", "iou_similarity",
    "box_coder", "polygon_box_transform", "yolov3_loss", "yolo_box",
    "box_clip", "multiclass_nms", "locality_aware_nms",
    "retinanet_detection_output", "distribute_fpn_proposals",
    "box_decoder_and_assign", "collect_fpn_proposals",
    "roi_align", "roi_pool",
]


def _mk(helper, dtype="float32", shape=None, lod_level=0):
    v = helper.create_variable_for_type_inference(dtype)
    if shape is not None:
        v.shape = tuple(shape)
    v.lod_level = lod_level
    return v


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = _mk(helper)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", **locals())
    boxes = _mk(helper)
    var = _mk(helper)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": [float(s) for s in
                             (min_sizes if isinstance(min_sizes,
                                                      (list, tuple))
                              else [min_sizes])],
               "max_sizes": [float(s) for s in (max_sizes or [])]
               if max_sizes else [],
               "aspect_ratios": [float(a) for a in aspect_ratios],
               "variances": [float(v) for v in variance],
               "flip": flip, "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": float(offset),
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return boxes, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", **locals())
    boxes = _mk(helper)
    var = _mk(helper)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"densities": [int(d) for d in densities or []],
               "fixed_sizes": [float(s) for s in fixed_sizes or []],
               "fixed_ratios": [float(r) for r in fixed_ratios or [1.0]],
               "variances": [float(v) for v in variance],
               "clip": clip, "step_w": float(steps[0]),
               "step_h": float(steps[1]), "offset": float(offset)})
    if flatten_to_2d:
        boxes = nn.reshape(boxes, [-1, 4])
        var = nn.reshape(var, [-1, 4])
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None,
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", **locals())
    anchors = _mk(helper)
    var = _mk(helper)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": [float(s) for s in anchor_sizes],
               "aspect_ratios": [float(a) for a in aspect_ratios or [1.0]],
               "variances": [float(v) for v in variance],
               "stride": [float(s) for s in stride],
               "offset": float(offset)})
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper("box_coder", **locals())
    out = _mk(helper)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": int(axis)}
    if prior_box_var is None:
        pass
    elif hasattr(prior_box_var, "name"):
        inputs["PriorBoxVar"] = [prior_box_var]
    elif isinstance(prior_box_var, (list, tuple)):
        # the reference API also accepts a 4-float list; it rides as an attr
        attrs["variance"] = [float(v) for v in prior_box_var]
    else:
        raise TypeError("prior_box_var must be a Variable, a 4-float "
                        "list/tuple, or None; got %r" % (prior_box_var,))
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]}, attrs=attrs)
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", **locals())
    out = _mk(helper, shape=input.shape)
    helper.append_op(type="box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", **locals())
    out = _mk(helper, shape=input.shape)
    helper.append_op(type="polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", **locals())
    idx = _mk(helper, dtype="int32")
    dist = _mk(helper)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx],
                 "ColToRowMatchDist": [dist]},
        attrs={"match_type": match_type,
               "dist_threshold": float(dist_threshold)})
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", **locals())
    out = _mk(helper)
    out_w = _mk(helper)
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [out_w]},
        attrs={"mismatch_value": mismatch_value})
    return out, out_w


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss", **locals())
    out = _mk(helper, shape=x.shape)
    helper.append_op(
        type="sigmoid_focal_loss",
        inputs={"X": [x], "Label": [label], "FgNum": [fg_num]},
        outputs={"Out": [out]},
        attrs={"gamma": float(gamma), "alpha": float(alpha)})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", **locals())
    boxes = _mk(helper)
    scores = _mk(helper)
    helper.append_op(
        type="yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={"anchors": [int(a) for a in anchors],
               "class_num": int(class_num),
               "conf_thresh": float(conf_thresh),
               "downsample_ratio": int(downsample_ratio),
               "clip_bbox": clip_bbox})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, name=None):
    helper = LayerHelper("yolov3_loss", **locals())
    loss = _mk(helper, shape=(-1,))
    helper.append_op(
        type="yolov3_loss",
        inputs={"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]},
        outputs={"Loss": [loss]},
        attrs={"anchors": [int(a) for a in anchors],
               "anchor_mask": [int(m) for m in anchor_mask],
               "class_num": int(class_num),
               "ignore_thresh": float(ignore_thresh),
               "downsample_ratio": int(downsample_ratio)})
    return loss


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, return_index=False, name=None):
    """Fixed-size output [N, keep_top_k, 6] (label, score, box), padded
    with label -1 (TPU static-shape redesign of the LoD output). With
    ``return_index`` also returns the [N, keep_top_k] source-box index
    (-1 on pad rows) — the multiclass_nms2 surface."""
    helper = LayerHelper("multiclass_nms", **locals())
    out = _mk(helper)
    outputs = {"Out": [out]}
    if return_index:
        index = _mk(helper, dtype="int32")
        outputs["Index"] = [index]
    helper.append_op(
        type="multiclass_nms2" if return_index else "multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs=outputs,
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k),
               "keep_top_k": int(keep_top_k),
               "nms_threshold": float(nms_threshold),
               "nms_eta": float(nms_eta),
               "background_label": int(background_label),
               "normalized": normalized})
    if return_index:
        return out, index
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    helper = LayerHelper("locality_aware_nms", **locals())
    out = _mk(helper)
    helper.append_op(
        type="locality_aware_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
               "nms_threshold": float(nms_threshold),
               "nms_eta": float(nms_eta),
               "background_label": int(background_label),
               "normalized": normalized})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """Decode + per-class NMS (reference detection.py detection_output).
    With ``return_index`` returns ``(out, index)`` like the reference."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold,
                          background_label=background_label,
                          nms_eta=nms_eta, return_index=return_index)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """TPU-native: gt_box/gt_label are DENSE [N, B, 4]/[N, B] (pad with
    zero-area boxes) instead of LoD; mining is mask-based (see op)."""
    helper = LayerHelper("ssd_loss", **locals())
    loss = _mk(helper)
    inputs = {"Location": [location], "Confidence": [confidence],
              "GtBox": [gt_box], "GtLabel": [gt_label],
              "PriorBox": [prior_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="ssd_loss", inputs=inputs, outputs={"Loss": [loss]},
        attrs={"background_label": int(background_label),
               "overlap_threshold": float(overlap_threshold),
               "neg_pos_ratio": float(neg_pos_ratio),
               "loc_loss_weight": float(loc_loss_weight),
               "conf_loss_weight": float(conf_loss_weight)})
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD heads (reference detection.py multi_box_head): conv loc/conf
    per feature map + concatenated priors."""
    if min_sizes is None:
        # reference ratio interpolation
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        if num_layer > 2:
            step = int((max_ratio - min_ratio) / (num_layer - 2))
            for ratio in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * ratio / 100.0)
                max_sizes.append(base_size * (ratio + step) / 100.0)
            min_sizes = [base_size * 0.1] + min_sizes
            max_sizes = [base_size * 0.2] + max_sizes
        else:
            min_sizes = [base_size * 0.1, base_size * 0.2]
            max_sizes = [base_size * 0.2, base_size * 0.3]
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = None
        if max_sizes:
            mx = max_sizes[i] if isinstance(max_sizes[i], (list, tuple)) \
                else [max_sizes[i]]
        ar = aspect_ratios[i] if isinstance(
            aspect_ratios[i], (list, tuple)) else [aspect_ratios[i]]
        stp = steps[i] if steps else (step_w[i] if step_w else 0.0,
                                      step_h[i] if step_h else 0.0)
        if not isinstance(stp, (list, tuple)):
            stp = (stp, stp)
        box, var = prior_box(feat, image, ms, mx, ar, variance, flip, clip,
                             stp, offset,
                             min_max_aspect_ratios_order=(
                                 min_max_aspect_ratios_order))
        n_priors = 1
        full = 1 + (len([a for a in ar if abs(a - 1.0) > 1e-6]) *
                    (2 if flip else 1))
        n_priors = len(ms) * full + (len(mx) if mx else 0)
        loc = nn.conv2d(feat, n_priors * 4, kernel_size, stride=stride,
                        padding=pad)
        conf = nn.conv2d(feat, n_priors * num_classes, kernel_size,
                         stride=stride, padding=pad)
        # [N, P*4, H, W] -> [N, H*W*P, 4]
        loc = nn.transpose(loc, [0, 2, 3, 1])
        loc = nn.reshape(loc, [0, -1, 4])
        conf = nn.transpose(conf, [0, 2, 3, 1])
        conf = nn.reshape(conf, [0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_l.append(nn.reshape(box, [-1, 4]))
        vars_l.append(nn.reshape(var, [-1, 4]))
    mbox_locs = tensor.concat(locs, axis=1)
    mbox_confs = tensor.concat(confs, axis=1)
    all_boxes = tensor.concat(boxes_l, axis=0)
    all_vars = tensor.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, all_boxes, all_vars


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    helper = LayerHelper("rpn_target_assign", **locals())
    loc_idx = _mk(helper, dtype="int32")
    score_idx = _mk(helper, dtype="int32")
    tgt_lbl = _mk(helper, dtype="int32")
    tgt_box = _mk(helper)
    in_w = _mk(helper)
    helper.append_op(
        type="rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes]},
        outputs={"LocationIndex": [loc_idx], "ScoreIndex": [score_idx],
                 "TargetLabel": [tgt_lbl], "TargetBBox": [tgt_box],
                 "BBoxInsideWeight": [in_w]},
        attrs={"rpn_batch_size_per_im": int(rpn_batch_size_per_im),
               "rpn_fg_fraction": float(rpn_fg_fraction),
               "rpn_positive_overlap": float(rpn_positive_overlap),
               "rpn_negative_overlap": float(rpn_negative_overlap)})
    return loc_idx, score_idx, tgt_lbl, tgt_box, in_w


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4):
    helper = LayerHelper("retinanet_target_assign", **locals())
    loc_idx = _mk(helper, dtype="int32")
    score_idx = _mk(helper, dtype="int32")
    tgt_lbl = _mk(helper, dtype="int32")
    tgt_box = _mk(helper)
    in_w = _mk(helper)
    fg = _mk(helper, dtype="int32")
    helper.append_op(
        type="retinanet_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes]},
        outputs={"LocationIndex": [loc_idx], "ScoreIndex": [score_idx],
                 "TargetLabel": [tgt_lbl], "TargetBBox": [tgt_box],
                 "BBoxInsideWeight": [in_w], "ForegroundNumber": [fg]},
        attrs={"rpn_positive_overlap": float(positive_overlap),
               "rpn_negative_overlap": float(negative_overlap)})
    return loc_idx, score_idx, tgt_lbl, tgt_box, in_w, fg


def retinanet_detection_output(bboxes, scores, im_info, score_threshold=0.05,
                               nms_top_k=1000, keep_top_k=100,
                               nms_threshold=0.3, nms_eta=1.0):
    """Decode-free variant: inputs are already per-level boxes+scores;
    concatenate levels, then the shared fixed-size NMS core."""
    all_b = tensor.concat(bboxes, axis=1) if isinstance(bboxes, (list,
                                                                 tuple)) \
        else bboxes
    all_s = tensor.concat(scores, axis=1) if isinstance(scores, (list,
                                                                 tuple)) \
        else scores
    # scores [N, M, C] -> [N, C, M]
    all_s = nn.transpose(all_s, [0, 2, 1])
    return multiclass_nms(all_b, all_s, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, nms_eta=nms_eta,
                          background_label=-1)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    helper = LayerHelper("generate_proposals", **locals())
    rois = _mk(helper)
    probs = _mk(helper)
    rois_num = _mk(helper, dtype="int32")
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                 "RpnRoisNum": [rois_num]},
        attrs={"pre_nms_topN": int(pre_nms_top_n),
               "post_nms_topN": int(post_nms_top_n),
               "nms_thresh": float(nms_thresh),
               "min_size": float(min_size), "eta": float(eta)})
    if return_rois_num:
        return rois, probs, rois_num
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """Static-shape redesign: labels/targets for ALL rois; sampling is
    expressed by the returned weights (reference samples an index list)."""
    # DistMat rows are gt, columns are rois (see ops _bipartite_match)
    iou = iou_similarity(gt_boxes, rpn_rois)
    idx, dist = bipartite_match(iou, "per_prediction", fg_thresh)
    labels, lw = target_assign(gt_classes, idx, mismatch_value=0)
    tgts, tw = target_assign(gt_boxes, idx, mismatch_value=0)
    return rpn_rois, labels, tgts, tw, lw


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    raise NotImplementedError(
        "generate_mask_labels needs polygon rasterization; Mask R-CNN "
        "targets are out of scope for the TPU build (open an issue with "
        "your use case)")


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    raise NotImplementedError(
        "roi_perspective_transform (OCR quad warping) is not implemented "
        "on TPU; use roi_align for axis-aligned regions")


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    helper = LayerHelper("distribute_fpn_proposals", **locals())
    n = max_level - min_level + 1
    outs = [_mk(helper) for _ in range(n)]
    restore = _mk(helper, dtype="int32")
    helper.append_op(
        type="distribute_fpn_proposals",
        inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": outs, "RestoreIndex": [restore]},
        attrs={"min_level": int(min_level), "max_level": int(max_level),
               "refer_level": int(refer_level),
               "refer_scale": float(refer_scale)})
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    helper = LayerHelper("collect_fpn_proposals", **locals())
    out = _mk(helper)
    helper.append_op(
        type="collect_fpn_proposals",
        inputs={"MultiLevelRois": multi_rois,
                "MultiLevelScores": multi_scores},
        outputs={"FpnRois": [out]},
        attrs={"post_nms_topN": int(post_nms_top_n)})
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip_v=None, name=None):
    helper = LayerHelper("box_decoder_and_assign", **locals())
    decoded = _mk(helper)
    assigned = _mk(helper)
    helper.append_op(
        type="box_decoder_and_assign",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box], "BoxScore": [box_score]},
        outputs={"DecodeBox": [decoded], "OutputAssignBox": [assigned]})
    return decoded, assigned


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    helper = LayerHelper("roi_align", **locals())
    out = _mk(helper)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    helper.append_op(
        type="roi_align", inputs=inputs, outputs={"Out": [out]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale),
               "sampling_ratio": int(sampling_ratio)})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    helper = LayerHelper("roi_pool", **locals())
    out = _mk(helper)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    helper.append_op(
        type="roi_pool", inputs=inputs, outputs={"Out": [out]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale)})
    return out
