"""Python-operator sugar on Variable (reference ``layers/math_op_patch.py``)."""

import numpy as np

from .. import framework


def binary_op(x, other, op_type, reverse=False):
    from ..layer_helper import LayerHelper
    from .tensor import fill_constant

    helper = LayerHelper(op_type)
    if not isinstance(other, framework.Variable):
        val = float(other)
        # scalar + elementwise → use scale op where possible (cheaper IR)
        if op_type == "elementwise_add" and not reverse:
            from .nn import scale as scale_layer

            return scale_layer(x, scale=1.0, bias=val)
        shape = [1]
        other = fill_constant(shape, framework.dtype_str(x.dtype), val)
    a, b = (other, x) if reverse else (x, other)
    out = helper.create_variable_for_type_inference(
        "bool" if op_type in ("less_than", "less_equal", "greater_than",
                              "greater_equal", "equal", "not_equal") else a.dtype
    )
    helper.append_op(type=op_type, inputs={"X": [a], "Y": [b]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out
