"""Control-flow layers (reference ``layers/control_flow.py``: While, Switch,
cond, case, switch_case, StaticRNN, while_loop, increment, less_than, ...).

Comparison/logical/increment live in nn/elementwise; this module adds the
block-structured constructs. Sub-blocks are real IR blocks; execution lowers
them to lax.cond/while_loop/scan (ops/control_flow.py).
"""

import numpy as np

from .. import framework
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = ["While", "Switch", "cond", "case", "switch_case", "while_loop",
           "StaticRNN", "increment", "less_than", "less_equal", "greater_than",
           "greater_equal", "equal", "not_equal", "is_empty", "Print",
           "array_write", "array_read", "array_length", "create_array"]


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, force_cpu=None, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def is_empty(x, cond=None):
    from . import tensor

    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    # static shapes: emptiness is a compile-time property
    empty = int(np.prod([d for d in x.shape if d >= 0])) == 0
    return tensor.assign(np.asarray([empty]), cond)


def Print(input, first_n=-1, message=None, summarize=-1, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print via jax.debug.print host callback (reference print_op)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or ""})
    return out


def _register_print_op():
    from ..registry import register

    @register("print")
    def _print(ctx, op):
        import jax

        x = ctx.get_input(op, "In")
        msg = op.attr("message", "")
        jax.debug.print(msg + "{x}", x=x)
        ctx.set_output(op, "Out", x)


_register_print_op()


class While:
    """Reference ``layers/control_flow.py`` While: body mutates outer vars;
    the condition var must be reassigned inside the body.

        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...
            layers.increment(i)
            layers.less_than(i, n, cond=cond)
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    import contextlib

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_idx = program.current_block_idx
        sub_block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        program.current_block().append_op(
            "while",
            inputs={"Condition": [self.cond_var]},
            outputs={},
            attrs={"sub_block": sub_block.idx},
        )


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """Functional while (newer-paddle-style API; also the cleanest XLA
    mapping). cond/body are python callables building sub-blocks.

    ``maximum_trip_count``: if given, lowers to a bounded masked scan, which
    is reverse-differentiable (XLA cannot reverse-diff unbounded loops; the
    reference pays the same cost by re-running while bodies in while_grad)."""
    helper = LayerHelper("while_loop", name=name)
    program = helper.main_program

    # build condition sub-block
    cond_block = program._create_block()
    cond_out = cond(*loop_vars)
    program._rollback()

    body_block = program._create_block()
    body_outs = body(*loop_vars)
    program._rollback()
    body_outs = body_outs if isinstance(body_outs, (list, tuple)) else [body_outs]

    out_vars = [
        helper.block.create_var(
            name=helper.name_prefix + ".out%d" % i, shape=v.shape, dtype=v.dtype)
        for i, v in enumerate(loop_vars)
    ]
    helper.append_op(
        type="while_loop",
        inputs={"LoopVars": list(loop_vars)},
        outputs={"Out": out_vars},
        attrs={
            "cond_block": cond_block.idx,
            "body_block": body_block.idx,
            "cond_out": cond_out.name,
            "loop_var_names": [v.name for v in loop_vars],
            "body_out_names": [v.name for v in body_outs],
            "out_names": [v.name for v in out_vars],
            "maximum_trip_count": maximum_trip_count or 0,
        },
    )
    return out_vars


def _register_while_loop_op():
    from ..registry import LowerCtx, lower_op, register, registry

    @register("while_loop")
    def _while_loop(ctx, op):
        import jax

        program = ctx.program
        cond_block = program.block(op.attr("cond_block"))
        body_block = program.block(op.attr("body_block"))
        names = op.attr("loop_var_names")
        body_out_names = op.attr("body_out_names")
        cond_out = op.attr("cond_out")
        out_names = op.attr("out_names")
        snapshot = dict(ctx.env)

        def run_block(block, env):
            sub = LowerCtx(block, env, ctx.rng_key, mesh=ctx.mesh)
            for o in block.ops:
                lower_op(sub, o)

        def cond_fun(carry):
            env = dict(snapshot)
            env.update(dict(zip(names, carry)))
            run_block(cond_block, env)
            c = env[cond_out]
            return c.reshape(()) if hasattr(c, "reshape") else c

        def body_fun(carry):
            env = dict(snapshot)
            env.update(dict(zip(names, carry)))
            run_block(body_block, env)
            return tuple(env[n] for n in body_out_names)

        init = tuple(ctx.get(n) for n in names)
        max_trips = op.attr("maximum_trip_count", 0)
        if max_trips:
            # bounded masked scan: differentiable (while_grad analogue)
            def scan_step(carry, _):
                active = cond_fun(carry)
                new = body_fun(carry)
                import jax.numpy as jnp

                merged = tuple(
                    jnp.where(active, n_, c_) for n_, c_ in zip(new, carry)
                )
                return merged, None

            final, _ = jax.lax.scan(scan_step, init, None, length=max_trips)
        else:
            final = jax.lax.while_loop(cond_fun, body_fun, init)
        for n, v in zip(out_names, final):
            ctx.set(n, v)


_register_while_loop_op()


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional conditional (reference ``layers.cond``)."""
    helper = LayerHelper("cond", name=name)
    program = helper.main_program

    true_block = program._create_block()
    true_out = true_fn() if true_fn is not None else None
    program._rollback()
    false_block = program._create_block()
    false_out = false_fn() if false_fn is not None else None
    program._rollback()

    def _flat(o):
        if o is None:
            return []
        return list(o) if isinstance(o, (list, tuple)) else [o]

    t_outs, f_outs = _flat(true_out), _flat(false_out)
    assert len(t_outs) == len(f_outs), "cond branches must return same arity"
    outs = [
        helper.block.create_var(name=helper.name_prefix + ".out%d" % i,
                                shape=v.shape, dtype=v.dtype)
        for i, v in enumerate(t_outs)
    ]
    helper.append_op(
        type="cond",
        inputs={"Cond": [pred]},
        outputs={"Out": outs},
        attrs={
            "true_block": true_block.idx,
            "false_block": false_block.idx,
            "true_outs": [v.name for v in t_outs],
            "false_outs": [v.name for v in f_outs],
            "out_names": [v.name for v in outs],
        },
    )
    if true_out is None:
        return None
    if isinstance(true_out, (list, tuple)):
        return outs
    return outs[0]


def case(pred_fn_pairs, default=None, name=None):
    """Reference ``layers.case``: first true pred wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is None:
        default = fn
    return cond(pred, fn, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference ``layers.switch_case``: dispatch on integer index."""
    from . import tensor

    pairs = []
    items = branch_fns.items() if isinstance(branch_fns, dict) else enumerate(branch_fns)
    for idx, fn in items:
        iv = tensor.fill_constant([1], "int64", int(idx))
        pred = equal(branch_index, iv)
        pairs.append((pred, fn))
    return case(pairs, default)


class Switch:
    """Reference Switch/case blocks used for LR scheduling. Implemented over
    cond chains; usable only in the `with switch.case(cond): assign(...)`
    idiom where each branch assigns the same output vars."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._cases = []  # (cond_var_or_None, block_idx)

    import contextlib

    @contextlib.contextmanager
    def case(self, condition):
        program = self.helper.main_program
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self._cases.append((condition, blk.idx))

    @contextlib.contextmanager
    def default(self):
        program = self.helper.main_program
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self._cases.append((None, blk.idx))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        helper = self.helper
        helper.append_op(
            type="switch",
            inputs={"Conds": [c for c, _ in self._cases if c is not None]},
            outputs={},
            attrs={
                "blocks": [b for _, b in self._cases],
                "has_default": any(c is None for c, _ in self._cases),
            },
        )
        return False


def _register_switch_op():
    from ..registry import LowerCtx, lower_op, register, registry

    @register("switch")
    def _switch(ctx, op):
        import jax

        program = ctx.program
        blocks = [program.block(i) for i in op.attr("blocks")]
        conds = ctx.get_inputs(op, "Conds")
        # carried = union of writes across branches present in outer env
        carried = []
        for blk in blocks:
            for op2 in blk.ops:
                for n in op2.output_arg_names():
                    if n in ctx.env and n not in carried:
                        carried.append(n)
        snapshot = dict(ctx.env)

        def make_branch(blk):
            def fn(vals):
                env = dict(snapshot)
                env.update(dict(zip(carried, vals)))
                sub = LowerCtx(blk, env, ctx.rng_key, mesh=ctx.mesh)
                for o in blk.ops:
                    lower_op(sub, o)
                return tuple(env[n] for n in carried)

            return fn

        vals = tuple(ctx.env[n] for n in carried)
        # chain: last-to-first so first true cond wins
        n_conds = len(conds)
        result = vals
        if op.attr("has_default"):
            result = make_branch(blocks[-1])(vals)
        for i in range(n_conds - 1, -1, -1):
            c = conds[i].reshape(()) if hasattr(conds[i], "reshape") else conds[i]
            result = jax.lax.cond(c, make_branch(blocks[i]),
                                  lambda v, _r=result: _r, vals)
        for n, v in zip(carried, result):
            ctx.set(n, v)


_register_switch_op()


class StaticRNN:
    """Static (unrolled-length) RNN over time-major inputs, lowered to
    lax.scan (reference StaticRNN / recurrent_op.cc).

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: (T, B, D)
            h_prev = rnn.memory(init=h0)     # or shape/value init
            h = layers.fc(x_t, ...)          # build step computation
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        outs = rnn()   # (T, B, ...) stacked outputs
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._block = None
        self._seq_inputs = []  # (outer var, in-block var)
        self._memories = []  # (init outer var, pre var, post var or None)
        self._outputs = []
        self._finalized = False

    import contextlib

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
            self._append_op()

    def step_input(self, x):
        blk = self._block
        v = blk.create_var(name=self.helper.name_prefix + ".x%d" % len(self._seq_inputs),
                           shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._seq_inputs.append((x, v))
        return v

    def memory(self, init=None, shape=None, value=0.0, batch_ref=None,
               dtype="float32"):
        from . import tensor

        if init is None:
            assert shape is not None
            # build init in the PARENT block
            program = self.helper.main_program
            cur = program.current_block_idx
            program.current_block_idx = self._block.parent_idx
            init = tensor.fill_constant(shape, dtype, value)
            program.current_block_idx = cur
        pre = self._block.create_var(
            name=self.helper.name_prefix + ".mem%d" % len(self._memories),
            shape=init.shape, dtype=init.dtype)
        self._memories.append([init, pre, None])
        return pre

    def update_memory(self, mem, new):
        for m in self._memories:
            if m[1] is mem:
                m[2] = new
                return
        raise ValueError("update_memory: unknown memory var")

    def step_output(self, o):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _append_op(self):
        helper = self.helper
        self._out_vars = [
            helper.block.create_var(
                name=helper.name_prefix + ".out%d" % i,
                shape=(-1,) + tuple(o.shape), dtype=o.dtype)
            for i, o in enumerate(self._outputs)
        ]
        helper.append_op(
            type="static_rnn",
            inputs={"SeqIn": [x for x, _ in self._seq_inputs],
                    "MemInit": [m[0] for m in self._memories]},
            outputs={"Out": self._out_vars},
            attrs={
                "sub_block": self._block.idx,
                "seq_inputs": [x.name for x, _ in self._seq_inputs],
                "step_inputs": [v.name for _, v in self._seq_inputs],
                "mem_init": [m[0].name for m in self._memories],
                "mem_pre": [m[1].name for m in self._memories],
                "mem_post": [m[2].name for m in self._memories],
                "step_outputs": [o.name for o in self._outputs],
                "out_names": [v.name for v in self._out_vars],
                "final_mem_names": [],
            },
        )

    def __call__(self):
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


# -- bounded TensorArray -----------------------------------------------------
#
# Reference LoDTensorArray (layers at control_flow.py:1113 array_write,
# :1177 create_array, :1466 array_read, :1578 array_length) re-designed
# for static shapes: a fixed-capacity [bound, ...element] buffer + an
# int32 length side-bound to ``name + "@ALEN"`` (design note in
# fluid/ops/control_flow.py). Arrays written inside While/StaticRNN
# blocks must be created with ``element_shape`` (and ``bound``) so the
# loop carry holds its final shape from the first iteration.

DEFAULT_TENSOR_ARRAY_BOUND = 128


def create_array(dtype, element_shape=None, bound=None):
    """Create a bounded tensor array. ``element_shape``/``bound`` are
    TPU-native extensions: pass them when the array is written inside a
    loop block (the buffer must pre-exist with its final shape); plain
    straight-line writes may omit them (the first write sizes the
    buffer to ``bound`` x its element shape)."""
    helper = LayerHelper("create_array")
    out = helper.create_variable_for_type_inference(dtype)
    out.is_tensor_array = True
    out._ta_bound = int(bound or DEFAULT_TENSOR_ARRAY_BOUND)
    helper.append_op(
        type="create_array", inputs={}, outputs={"Out": [out]},
        attrs={"dtype": dtype,
               "element_shape": [int(s) for s in element_shape]
               if element_shape else [],
               "bound": out._ta_bound})
    return out


def _as_index_var(i):
    from . import tensor

    if isinstance(i, int):
        return tensor.fill_constant([1], "int32", i)
    return i


def array_write(x, i, array=None):
    """Write ``x`` into slot ``i``; returns the array (reference
    ``control_flow.py:1113``). ``i`` may be a python int or an int
    Variable (e.g. a loop counter)."""
    if array is None:
        array = create_array(x.dtype)
    helper = LayerHelper("array_write")
    helper.append_op(
        type="array_write",
        inputs={"X": [x], "I": [_as_index_var(i)], "Array": [array]},
        outputs={"Out": [array]},
        attrs={"bound": getattr(array, "_ta_bound",
                                DEFAULT_TENSOR_ARRAY_BOUND)})
    return array


def array_read(array, i):
    """Read slot ``i`` (reference ``control_flow.py:1466``)."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type="array_read",
                     inputs={"X": [array], "I": [_as_index_var(i)]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    """Number of written slots, int32 [1] (reference
    ``control_flow.py:1578``; int64 there — x64 stays off under JAX)."""
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int32")
    out.shape = (1,)
    helper.append_op(type="array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out
