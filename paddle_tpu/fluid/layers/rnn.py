"""RNN / decoding layers — reference ``python/paddle/fluid/layers/rnn.py``
(15 public fns: cells, rnn(), dynamic_* fused RNNs, beam search).

TPU-native design:
* ``dynamic_lstm/dynamic_lstmp/dynamic_gru`` lower to ONE ``lax.scan`` over
  a padded layout packed from bounded-LoD token rows (ops/rnn_ops.py) —
  the reference's batch-reorder machinery (math/sequence2batch.h) is gone.
* ``rnn(cell, ...)`` unrolls the cell at graph-build time over the STATIC
  time dimension (XLA re-rolls/pipelines it); masking by sequence_length
  keeps state frozen past each row's length.
* ``dynamic_decode`` unrolls to ``max_step_num`` with a finished mask (XLA
  needs a static trip bound; the reference's early-exit while-loop becomes
  masked ticks that XLA can still schedule densely).
* ``beam_search`` / ``gather_tree`` are dense [batch, beam] ops — no LoD
  beam bookkeeping (reference beam_search_op.cc walks LoD levels).
"""

import numpy as np

from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from . import control_flow, nn, tensor

__all__ = [
    "RNNCell", "GRUCell", "LSTMCell", "Decoder", "BeamSearchDecoder", "rnn",
    "dynamic_decode", "dynamic_lstm", "dynamic_lstmp", "dynamic_gru",
    "gru_unit", "lstm_unit", "lstm", "beam_search", "beam_search_decode",
    "gather_tree",
]


# ---------------------------------------------------------------------------
# fused (LoD) recurrences
# ---------------------------------------------------------------------------


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """Reference ``layers/rnn.py dynamic_lstm`` / ``lstm_op.cc``; input is
    the pre-projected [total, 4H] gate tensor (x @ Wx done by an fc)."""
    helper = LayerHelper("dynamic_lstm", **locals())
    H = size // 4
    w = helper.create_parameter(param_attr, [H, 4 * H], dtype)
    bias_size = 7 * H if use_peepholes else 4 * H
    b = helper.create_parameter(bias_attr, [1, bias_size], dtype,
                                is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    hidden.shape = cell.shape = (-1, H)
    hidden.lod_level = cell.lod_level = 1
    inputs = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="dynamic_lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32",
                  cell_clip=None, proj_clip=None, name=None):
    helper = LayerHelper("dynamic_lstmp", **locals())
    H = size // 4
    w = helper.create_parameter(param_attr, [proj_size, 4 * H], dtype)
    wp = helper.create_parameter(None, [H, proj_size], dtype)
    bias_size = 7 * H if use_peepholes else 4 * H
    b = helper.create_parameter(bias_attr, [1, bias_size], dtype,
                                is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    proj.shape, cell.shape = (-1, proj_size), (-1, H)
    proj.lod_level = cell.lod_level = 1
    inputs = {"Input": [input], "Weight": [w], "ProjWeight": [wp],
              "Bias": [b]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="dynamic_lstmp", inputs=inputs,
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation,
               "cell_clip": float(cell_clip or 0.0)})
    return proj, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None,
                origin_mode=False, name=None):
    helper = LayerHelper("dynamic_gru", **locals())
    dtype = "float32"
    w = helper.create_parameter(param_attr, [size, 3 * size], dtype)
    b = helper.create_parameter(bias_attr, [1, 3 * size], dtype,
                                is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    hidden.shape = (-1, size)
    hidden.lod_level = 1
    inputs = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="dynamic_gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"is_reverse": is_reverse, "gate_activation": gate_activation,
               "activation": candidate_activation,
               "origin_mode": origin_mode})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """One GRU step (reference gru_unit). ``size`` is 3*H like the
    reference; input is the pre-projected [B, 3H] gates."""
    helper = LayerHelper("gru_unit", **locals())
    H = size // 3
    dtype = "float32"
    w = helper.create_parameter(param_attr, [H, 3 * H], dtype)
    b = helper.create_parameter(bias_attr, [1, 3 * H], dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    gate.shape = (-1, 3 * H)
    reset_h.shape = updated.shape = (-1, H)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden], "Weight": [w],
                "Bias": [b]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h],
                 "Hidden": [updated]},
        attrs={"activation": activation, "gate_activation": gate_activation,
               "origin_mode": origin_mode})
    return updated, reset_h, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step (reference lstm_unit): projects [x_t, h_prev] to 4H
    gates with an fc then applies the cell."""
    helper = LayerHelper("lstm_unit", **locals())
    H = hidden_t_prev.shape[-1]
    concat = tensor.concat([x_t, hidden_t_prev], axis=1)
    gates = nn.fc(concat, size=4 * H, param_attr=param_attr,
                  bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    c.shape = h.shape = (-1, H)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)})
    return h, c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """cudnn-LSTM capability (reference layers/rnn.py lstm): PADDED
    [seq, batch, in] input, stacked layers in one scan chain."""
    if is_bidirec:
        raise NotImplementedError("bidirectional cudnn-style lstm: compose "
                                  "two dynamic_lstm(is_reverse=) passes")
    helper = LayerHelper("cudnn_lstm", **locals())
    dtype = "float32"
    I = input.shape[-1]
    sizes = []
    for layer in range(num_layers):
        in_dim = I if layer == 0 else hidden_size
        sizes.append(in_dim * 4 * hidden_size + hidden_size * 4 * hidden_size
                     + 4 * hidden_size)
    w = helper.create_parameter(ParamAttr(initializer=default_initializer)
                                if default_initializer else None,
                                [int(np.sum(sizes))], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    out.shape = (-1, -1, hidden_size)
    helper.append_op(
        type="cudnn_lstm",
        inputs={"Input": [input], "InitH": [init_h], "InitC": [init_c],
                "W": [w]},
        outputs={"Out": [out], "LastH": [last_h], "LastC": [last_c]},
        attrs={"hidden_size": int(hidden_size),
               "num_layers": int(num_layers),
               "dropout_prob": float(dropout_prob), "is_test": is_test})
    return out, last_h, last_c


# ---------------------------------------------------------------------------
# cells + rnn()
# ---------------------------------------------------------------------------


class RNNCell:
    """Base cell (reference rnn.py RNNCell): ``call(inputs, states)`` builds
    one step's ops and returns (outputs, new_states)."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        shape = list(shape or [self.hidden_size])
        return tensor.fill_constant_batch_size_like(
            batch_ref, [-1] + shape, dtype, init_value,
            input_dim_idx=batch_dim_idx)

    @property
    def state_shape(self):
        return [self.hidden_size]


class GRUCell(RNNCell):
    """Parameters are created ONCE (lazily, at the first ``call``) and
    shared across every timestep — an unrolled rnn()/decode loop reuses the
    same recurrent weights, matching the reference's Layer-held params."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation="sigmoid", activation="tanh",
                 origin_mode=False, name=None):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = gate_activation
        self._act = activation
        self._origin = origin_mode
        self._name = name
        self._wx = self._wh = self._b = None

    def _named(self, attr, suffix):
        """An explicit cell name pins the param names, so a separately
        built program (e.g. a beam-decode graph) resolves the SAME
        persistables from scope as the training graph. A caller attr
        without a name gets the pinned name filled in (an attr WITH a
        name wins)."""
        if self._name is None:
            return attr
        from ..param_attr import ParamAttr

        pinned = "%s.%s" % (self._name, suffix)
        if attr is None:
            return ParamAttr(name=pinned)
        attr = ParamAttr._to_attr(attr)
        if attr is False:  # bias_attr=False = no param; pass through
            return attr
        if getattr(attr, "name", None) is None:
            import copy

            attr = copy.copy(attr)  # don't mutate a caller-shared attr
            attr.name = pinned
        return attr

    def _ensure_params(self, in_dim):
        if self._wx is not None:
            return
        helper = LayerHelper("gru_cell")
        H = self.hidden_size
        self._wx = helper.create_parameter(
            self._named(self._param_attr, "wx"), [in_dim, 3 * H], "float32")
        self._wh = helper.create_parameter(self._named(None, "wh"),
                                           [H, 3 * H], "float32")
        self._b = helper.create_parameter(
            self._named(self._bias_attr, "b"), [1, 3 * H], "float32",
            is_bias=True)

    def call(self, inputs, states):
        self._ensure_params(int(inputs.shape[-1]))
        helper = LayerHelper("gru_cell_step")
        H = self.hidden_size
        gates = helper.create_variable_for_type_inference("float32")
        gates.shape = (-1, 3 * H)
        helper.append_op(type="mul",
                         inputs={"X": [inputs], "Y": [self._wx]},
                         outputs={"Out": [gates]},
                         attrs={"x_num_col_dims": 1, "y_num_col_dims": 1})
        gate = helper.create_variable_for_type_inference("float32")
        reset_h = helper.create_variable_for_type_inference("float32")
        updated = helper.create_variable_for_type_inference("float32")
        gate.shape = (-1, 3 * H)
        reset_h.shape = updated.shape = (-1, H)
        unit_inputs = {"Input": [gates], "HiddenPrev": [states],
                       "Weight": [self._wh]}
        if self._b is not None:
            unit_inputs["Bias"] = [self._b]
        helper.append_op(
            type="gru_unit", inputs=unit_inputs,
            outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h],
                     "Hidden": [updated]},
            attrs={"activation": self._act,
                   "gate_activation": self._gate_act,
                   "origin_mode": self._origin})
        return updated, updated


class LSTMCell(RNNCell):
    """See GRUCell — parameters created once, shared across timesteps."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation="sigmoid", activation="tanh",
                 forget_bias=1.0, name=None):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._forget_bias = forget_bias
        self._name = name
        self._w = self._b = None

    _named = GRUCell._named

    def _ensure_params(self, in_dim):
        if self._w is not None:
            return
        helper = LayerHelper("lstm_cell")
        H = self.hidden_size
        self._w = helper.create_parameter(
            self._named(self._param_attr, "w"), [in_dim + H, 4 * H],
            "float32")
        self._b = helper.create_parameter(
            self._named(self._bias_attr, "b"), [1, 4 * H], "float32",
            is_bias=True)

    def call(self, inputs, states):
        h, c = states
        self._ensure_params(int(inputs.shape[-1]))
        helper = LayerHelper("lstm_cell_step")
        H = self.hidden_size
        concat = tensor.concat([inputs, h], axis=1)
        gates = helper.create_variable_for_type_inference("float32")
        gates.shape = (-1, 4 * H)
        helper.append_op(type="mul",
                         inputs={"X": [concat], "Y": [self._w]},
                         outputs={"Out": [gates]},
                         attrs={"x_num_col_dims": 1, "y_num_col_dims": 1})
        if self._b is not None:
            biased = helper.create_variable_for_type_inference("float32")
            biased.shape = (-1, 4 * H)
            helper.append_op(type="elementwise_add",
                             inputs={"X": [gates], "Y": [self._b]},
                             outputs={"Out": [biased]}, attrs={"axis": -1})
            gates = biased
        new_c = helper.create_variable_for_type_inference("float32")
        new_h = helper.create_variable_for_type_inference("float32")
        new_c.shape = new_h.shape = (-1, H)
        helper.append_op(
            type="lstm_unit",
            inputs={"X": [gates], "C_prev": [c]},
            outputs={"C": [new_c], "H": [new_h]},
            attrs={"forget_bias": float(self._forget_bias)})
        return new_h, [new_h, new_c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        mk = lambda: tensor.fill_constant_batch_size_like(
            batch_ref, [-1, self.hidden_size], dtype, init_value,
            input_dim_idx=batch_dim_idx)
        return [mk(), mk()]


def _map_state(states, fn):
    if isinstance(states, (list, tuple)):
        return [_map_state(s, fn) for s in states]
    return fn(states)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Unrolled cell recurrence over a PADDED batch (reference rnn.py
    ``rnn``): inputs [B, T, ...] (or [T, B, ...] when time_major). The
    time extent must be static — the graph unrolls T cell calls; XLA
    re-rolls and pipelines them."""
    T_axis = 0 if time_major else 1
    T = inputs.shape[T_axis]
    if T is None or int(T) < 0:
        raise ValueError("rnn() needs a static time dimension on TPU")
    T = int(T)
    if initial_states is None:
        # batch dim is axis 1 when time-major
        initial_states = cell.get_initial_states(
            inputs, batch_dim_idx=1 if time_major else 0)
    mask = None
    if sequence_length is not None:
        from . import sequence_lod

        mask = sequence_lod.sequence_mask(sequence_length, maxlen=T,
                                          dtype="float32")  # [B, T]
    states = initial_states
    outputs = []
    order = range(T - 1, -1, -1) if is_reverse else range(T)
    for t in order:
        if time_major:
            x_t = nn.squeeze(nn.slice(inputs, [0], [t], [t + 1]), [0])
        else:
            x_t = nn.squeeze(nn.slice(inputs, [1], [t], [t + 1]), [1])
        out, new_states = cell(x_t, states)
        if mask is not None:
            # freeze state past each row's length (reference _maybe_copy)
            m = nn.slice(mask, [1], [t], [t + 1])  # [B, 1]

            def gate(new, old, _m=m):
                return nn.elementwise_add(
                    nn.elementwise_mul(new, _m, axis=0),
                    nn.elementwise_mul(
                        old, nn.scale(_m, scale=-1.0, bias=1.0), axis=0))

            new_states = _zip_apply(new_states, states, gate)
        outputs.append(out)
        states = new_states
    if is_reverse:
        outputs = outputs[::-1]
    final = nn.stack(outputs, axis=T_axis)
    return final, states


def _flatten(s):
    if isinstance(s, (list, tuple)):
        out = []
        for x in s:
            out.extend(_flatten(x))
        return out
    return [s]


def _zip_apply(new, old, fn):
    if isinstance(new, (list, tuple)):
        return [_zip_apply(a, b, fn) for a, b in zip(new, old)]
    return fn(new, old)


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------


class Decoder:
    """Abstract decoder (reference rnn.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Dense [batch*beam] beam-search decoder (reference rnn.py
    BeamSearchDecoder). Candidate selection runs through the dense
    ``beam_search`` op; ``finalize`` backtracks with ``gather_tree``."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (repeat each row beam times)."""
        expanded = nn.unsqueeze(x, [1])
        tiled = nn.expand(expanded,
                          [1, beam_size] + [1] * (len(x.shape) - 1))
        return nn.reshape(tiled, [-1] + [int(s) for s in x.shape[1:]])

    def initialize(self, initial_cell_states):
        b = self.beam_size
        states = _map_state(initial_cell_states,
                            lambda s: self.tile_beam_merge_with_batch(s, b))
        ref = _flatten(states)[0]
        start = tensor.fill_constant_batch_size_like(
            ref, [-1, 1], "int64", self.start_token)  # [B*beam, 1]
        # log-prob 0 for beam 0, -1e9 for the rest so the first topk
        # draws all candidates from beam 0 (reference: lod-level trick)
        beam_pos = _beam_pos(ref, b)  # [B*beam, 1], 0..beam-1 repeating
        not_first = tensor.cast(beam_pos > _zeros_i64(ref), "float32")
        init_scores = nn.scale(not_first, scale=-1e9)
        inputs = self.embedding_fn(start) if self.embedding_fn else start
        finished = tensor.cast(
            tensor.fill_constant_batch_size_like(ref, [-1, 1], "int64", 0),
            "bool")
        return inputs, {"cell": states, "scores": init_scores,
                        "ids": start, "finished": finished}

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell = self.cell(inputs, states["cell"])
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        probs = nn.softmax(logits)  # [B*beam, V]
        sel_ids, sel_scores, parent = beam_search(
            pre_ids=states["ids"], pre_scores=states["scores"],
            ids=None, scores=probs, beam_size=self.beam_size,
            end_id=self.end_token, is_accumulated=False)
        next_cell = _map_state(next_cell, lambda s: nn.gather(s, parent))
        next_inputs = (self.embedding_fn(sel_ids)
                       if self.embedding_fn else sel_ids)
        finished = nn.gather(states["finished"], parent)
        now_end = tensor.cast(
            control_flow.equal(tensor.cast(sel_ids, "int64"),
                               _const_like_i64(sel_ids, self.end_token)),
            "bool")
        finished = nn.logical_or(finished, now_end)
        next_states = {"cell": next_cell, "scores": sel_scores,
                       "ids": sel_ids, "finished": finished}
        outputs = {"ids": sel_ids, "parents": parent,
                   "scores": sel_scores}
        return outputs, next_states, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        # outputs["ids"]/["parents"]: [T, B*beam, 1] stacked by dynamic_decode
        ids = nn.squeeze(outputs["ids"], [2])        # [T, B*beam]
        parents = nn.squeeze(outputs["parents"], [2]) \
            if len(outputs["parents"].shape) > 2 else outputs["parents"]
        seqs = gather_tree(ids, parents, end_token=self.end_token,
                           beam_size=self.beam_size)
        return {"sequences": seqs, "scores": final_states["scores"]}, \
            final_states


def _const_i64(v):
    return tensor.fill_constant([1], "int64", int(v))


def _zeros_i64(ref):
    return tensor.fill_constant_batch_size_like(ref, [-1, 1], "int64", 0)


def _beam_pos(ref, beam):
    """[B*beam, 1] int64 position-in-beam (0..beam-1 repeating)."""
    helper = LayerHelper("beam_pos")
    out = helper.create_variable_for_type_inference("int64")
    out.shape = (-1, 1)
    helper.append_op(type="beam_pos", inputs={"X": [ref]},
                     outputs={"Out": [out]}, attrs={"beam_size": int(beam)})
    return out


def _const_like_i64(ref, v):
    return tensor.fill_constant_batch_size_like(ref, [-1, 1], "int64", int(v))


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, **kwargs):
    """Unrolled decode loop (reference rnn.py dynamic_decode). XLA needs a
    static trip bound, so the loop runs exactly ``max_step_num`` masked
    ticks; finished beams keep emitting end tokens."""
    if max_step_num is None:
        raise ValueError("dynamic_decode needs max_step_num on TPU "
                         "(static trip bound)")
    init = decoder.initialize(inits)
    inputs, states = init[0], init[1]
    finished = init[2] if len(init) > 2 else None  # noqa: F841
    step_outputs = None
    for t in range(int(max_step_num)):
        outputs, states, inputs, finished = decoder.step(t, inputs, states)
        if step_outputs is None:
            step_outputs = {k: [v] for k, v in outputs.items()}
        else:
            for k, v in outputs.items():
                step_outputs[k].append(v)
    stacked = {k: nn.stack(v, axis=0) for k, v in step_outputs.items()}
    final, final_states = decoder.finalize(stacked, states, None)
    if not output_time_major and isinstance(final, dict):
        pass  # sequences stay [T, B*beam]; callers transpose as needed
    return final, final_states


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=True):
    """Dense beam-search candidate selection (reference beam_search_op.cc
    redesigned without LoD): rows are [batch*beam] grouped every
    ``beam_size``; emits top-k ids/scores per batch and the parent row
    each winner came from."""
    helper = LayerHelper("beam_search", **locals())
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference("float32")
    parent = helper.create_variable_for_type_inference("int32")
    sel_ids.shape = (-1, 1)
    sel_scores.shape = (-1, 1)
    parent.shape = (-1,)
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
              "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search", inputs=inputs,
        outputs={"selected_ids": [sel_ids],
                 "selected_scores": [sel_scores],
                 "parent_idx": [parent]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level), "is_accumulated": bool(is_accumulated)})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, parents=None,
                       name=None):
    """Backtrack stacked per-step (ids, parents)→ full sequences. In this
    dense redesign ``ids``/``scores`` are the [T, B*beam] stacks produced
    by the decode loop (the reference consumed LoD TensorArrays)."""
    helper = LayerHelper("beam_search_decode", **locals())
    out_ids = helper.create_variable_for_type_inference("int64")
    out_scores = helper.create_variable_for_type_inference("float32")
    inputs = {"Ids": [ids], "Scores": [scores]}
    if parents is not None:
        inputs["Parents"] = [parents]
    helper.append_op(
        type="beam_search_decode",
        inputs=inputs,
        outputs={"SentenceIds": [out_ids], "SentenceScores": [out_scores]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)})
    return out_ids, out_scores


def gather_tree(ids, parents, end_token=None, beam_size=None):
    """Backtrack beam parents into full sequences (reference
    gather_tree_op.cc): ids/parents [T, B*beam] (or [T, B, beam])."""
    helper = LayerHelper("gather_tree", **locals())
    out = helper.create_variable_for_type_inference(ids.dtype)
    out.shape = tuple(ids.shape)
    helper.append_op(type="gather_tree",
                     inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]},
                     attrs={"beam_size": -1 if beam_size is None
                            else int(beam_size)})
    return out
