"""Probability distributions — reference ``layers/distributions.py``
(Uniform, Normal, Categorical, MultivariateNormalDiag).

TPU-native: sampling draws from the threaded PRNG via the has_state random
ops (uniform_random/gaussian_random), so samples replay deterministically
under autodiff; densities/KL are closed-form op graphs.
"""

import math

from ..layer_helper import LayerHelper
from . import nn, tensor

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


def _as_var(value, dtype="float32"):
    import numpy as np

    if hasattr(value, "name"):
        return value
    arr = np.asarray(value, np.float32)
    return tensor.assign(arr.reshape(arr.shape if arr.ndim else (1,)))


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) (reference distributions.py Uniform)."""

    def __init__(self, low, high):
        self.low = _as_var(low)
        self.high = _as_var(high)

    def sample(self, shape, seed=0):
        helper = LayerHelper("uniform_sample")
        out = helper.create_variable_for_type_inference("float32")
        out.shape = tuple(shape)
        helper.append_op(
            type="uniform_random", inputs={}, outputs={"Out": [out]},
            attrs={"shape": list(shape), "min": 0.0, "max": 1.0,
                   "seed": seed, "dtype": "float32"})
        rng = nn.elementwise_sub(self.high, self.low)
        return nn.elementwise_add(
            nn.elementwise_mul(out, rng, axis=-1), self.low, axis=-1)

    def log_prob(self, value):
        rng = nn.elementwise_sub(self.high, self.low)
        lb = tensor.cast(value > self.low, "float32")
        ub = tensor.cast(value < self.high, "float32")
        return nn.log(nn.elementwise_div(
            nn.elementwise_mul(lb, ub), rng, axis=-1))

    def entropy(self):
        return nn.log(nn.elementwise_sub(self.high, self.low))


class Normal(Distribution):
    """N(loc, scale) (reference distributions.py Normal)."""

    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def sample(self, shape, seed=0):
        helper = LayerHelper("normal_sample")
        out = helper.create_variable_for_type_inference("float32")
        out.shape = tuple(shape)
        helper.append_op(
            type="gaussian_random", inputs={}, outputs={"Out": [out]},
            attrs={"shape": list(shape), "mean": 0.0, "std": 1.0,
                   "seed": seed, "dtype": "float32"})
        return nn.elementwise_add(
            nn.elementwise_mul(out, self.scale, axis=-1), self.loc, axis=-1)

    def log_prob(self, value):
        var = nn.elementwise_mul(self.scale, self.scale)
        delta = nn.elementwise_sub(value, self.loc, axis=-1)
        return nn.elementwise_sub(
            nn.elementwise_div(
                nn.scale(nn.elementwise_mul(delta, delta), scale=-0.5),
                var, axis=-1),
            nn.elementwise_add(
                nn.log(self.scale),
                tensor.fill_constant([1], "float32",
                                     0.5 * math.log(2 * math.pi)), axis=-1),
            axis=-1)

    def entropy(self):
        return nn.elementwise_add(
            nn.log(self.scale),
            tensor.fill_constant([1], "float32",
                                 0.5 + 0.5 * math.log(2 * math.pi)),
            axis=-1)

    def kl_divergence(self, other):
        """KL(self || other) for two diagonal normals."""
        var_ratio = nn.elementwise_div(self.scale, other.scale)
        var_ratio = nn.elementwise_mul(var_ratio, var_ratio)
        t1 = nn.elementwise_div(
            nn.elementwise_sub(self.loc, other.loc),
            other.scale, axis=-1)
        t1 = nn.elementwise_mul(t1, t1)
        return nn.scale(
            nn.elementwise_sub(
                nn.elementwise_add(var_ratio, t1),
                nn.elementwise_add(
                    nn.log(var_ratio),
                    tensor.fill_constant([1], "float32", 1.0), axis=-1)),
            scale=0.5)


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference Categorical)."""

    def __init__(self, logits):
        self.logits = logits

    def _log_softmax(self):
        return nn.log(nn.softmax(self.logits))

    def entropy(self):
        logp = self._log_softmax()
        p = nn.softmax(self.logits)
        return nn.scale(nn.reduce_sum(
            nn.elementwise_mul(p, logp), dim=-1), scale=-1.0)

    def log_prob(self, value):
        logp = self._log_softmax()
        oh = tensor.cast(nn.one_hot(
            tensor.cast(value, "int64"), self.logits.shape[-1]), "float32")
        return nn.reduce_sum(nn.elementwise_mul(logp, oh), dim=-1)

    def sample(self, shape=None, seed=0):
        """One draw per logit row. The reference Categorical has no
        sample(); a per-row draw is the natural extension — an explicit
        ``shape`` is not supported."""
        if shape is not None:
            raise NotImplementedError(
                "Categorical.sample draws one id per logit row; "
                "shape-based sampling is not supported")
        helper = LayerHelper("categorical_sample")
        out = helper.create_variable_for_type_inference("int64")
        helper.append_op(type="sampling_id",
                         inputs={"X": [nn.softmax(self.logits)]},
                         outputs={"Out": [out]}, attrs={"seed": seed})
        return out

    def kl_divergence(self, other):
        p = nn.softmax(self.logits)
        return nn.reduce_sum(
            nn.elementwise_mul(
                p, nn.elementwise_sub(self._log_softmax(),
                                      other._log_softmax())), dim=-1)


class MultivariateNormalDiag(Distribution):
    """N(loc, scale) — reference MultivariateNormalDiag: ``scale`` is the
    positive-definite diagonal COVARIANCE matrix [D, D] (docstring of
    ``layers/distributions.py:530``)."""

    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)  # [D, D] diagonal covariance

    def _var_diag(self):
        import numpy as np

        d = int(self.scale.shape[-1])
        eye = tensor.assign(np.eye(d, dtype=np.float32))
        return nn.reduce_sum(nn.elementwise_mul(self.scale, eye), dim=-1)

    def entropy(self):
        d = int(self.scale.shape[-1])
        logdet = nn.reduce_sum(nn.log(self._var_diag()))
        return nn.elementwise_add(
            tensor.fill_constant([1], "float32",
                                 0.5 * d * (1.0 + math.log(2 * math.pi))),
            nn.scale(logdet, scale=0.5))

    def log_prob(self, value):
        var = self._var_diag()
        delta = nn.elementwise_sub(value, self.loc, axis=-1)
        quad = nn.elementwise_div(
            nn.elementwise_mul(delta, delta), var, axis=-1)
        d = int(self.scale.shape[-1])
        return nn.elementwise_sub(
            nn.scale(nn.reduce_sum(quad, dim=-1), scale=-0.5),
            nn.elementwise_add(
                nn.scale(nn.reduce_sum(nn.log(var)), scale=0.5),
                tensor.fill_constant([1], "float32",
                                     0.5 * d * math.log(2 * math.pi)),
                axis=-1), axis=-1)

    def kl_divergence(self, other):
        """KL for diagonal-covariance normals:
        0.5 * sum(v1/v2 + (mu2-mu1)^2/v2 - 1 - log(v1/v2))."""
        v1, v2 = self._var_diag(), other._var_diag()
        ratio = nn.elementwise_div(v1, v2)
        t1 = nn.elementwise_sub(other.loc, self.loc, axis=-1)
        t1 = nn.elementwise_div(nn.elementwise_mul(t1, t1), v2, axis=-1)
        return nn.scale(nn.reduce_sum(
            nn.elementwise_sub(
                nn.elementwise_add(ratio, t1),
                nn.elementwise_add(
                    nn.log(ratio),
                    tensor.fill_constant([1], "float32", 1.0), axis=-1)),
            dim=-1), scale=0.5)
