"""Remaining Appendix-A layer fns (SURVEY): LoD rebinding, selected-rows
utilities, CVM, PSRoI pooling, chunk_eval, adaptive 3-D pooling, static
resize helpers — plus explicit, documented errors for the handful of
reference APIs whose dynamic-shape semantics have no sound XLA form."""

from ..framework import Variable
from ..layer_helper import LayerHelper
from . import nn, tensor

__all__ = [
    "lod_reset", "lod_append", "unique_with_counts",
    "merge_selected_rows", "get_tensor_from_selected_rows", "cvm",
    "continuous_value_model",
    "psroi_pool", "chunk_eval", "adaptive_pool3d", "image_resize_short",
    "scatter_nd", "crop_tensor", "fsp_matrix", "similarity_focus",
    "prroi_pool", "deformable_conv", "deformable_roi_pooling",
    "filter_by_instag", "reorder_lod_tensor_by_rank", "IfElse",
    "DynamicRNN", "tree_conv",
]


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = 1
    inputs = {"X": [x]}
    attrs = {}
    if y is not None:
        inputs["Y"] = [y]
    elif target_lod is not None:
        attrs["target_lod"] = [int(v) for v in target_lod]
    else:
        raise ValueError("lod_reset needs y or target_lod")
    helper.append_op(type="lod_reset", inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def lod_append(x, level):
    helper = LayerHelper("lod_append", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = getattr(x, "lod_level", 0) + 1
    helper.append_op(type="lod_append", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"level": [int(v) for v in level]})
    return out


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    count = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "Count": [count]})
    return out, index, count


def merge_selected_rows(x, name=None):
    helper = LayerHelper("merge_selected_rows", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.type = "selected_rows"
    out.shape = tuple(x.shape)  # keeps the dense height downstream
    helper.append_op(type="merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def get_tensor_from_selected_rows(x, height=None, name=None):
    """Densify a SelectedRows var. ``height`` defaults to the var's
    declared dense height (static shapes need it at build time)."""
    helper = LayerHelper("get_tensor_from_selected_rows", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if height is None:
        if x.shape and int(x.shape[0]) > 0:
            height = int(x.shape[0])
        else:
            raise ValueError(
                "pass height=: %r declares no static dense height"
                % (x.name,))
    helper.append_op(type="get_tensor_from_selected_rows",
                     inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"height": int(height)})
    return out


def cvm(input, cvm=None, use_cvm=True):
    helper = LayerHelper("cvm", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cvm", inputs={"X": [input]},
                     outputs={"Y": [out]}, attrs={"use_cvm": use_cvm})
    return out


# Reference name (``layers/nn.py`` continuous_value_model): alias of cvm.
continuous_value_model = cvm


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    helper = LayerHelper("psroi_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    helper.append_op(
        type="psroi_pool", inputs=inputs, outputs={"Out": [out]},
        attrs={"output_channels": int(output_channels),
               "spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width)})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    helper = LayerHelper("chunk_eval", **locals())
    mk = helper.create_variable_for_type_inference
    precision, recall, f1 = mk("float32"), mk("float32"), mk("float32")
    ni, nl, nc = mk("int32"), mk("int32"), mk("int32")
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length]
    helper.append_op(
        type="chunk_eval", inputs=inputs,
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1], "NumInferChunks": [ni],
                 "NumLabelChunks": [nl], "NumCorrectChunks": [nc]},
        attrs={"num_chunk_types": int(num_chunk_types),
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": [int(t) for t in
                                        (excluded_chunk_types or [])]})
    return precision, recall, f1, ni, nl, nc


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """Output bins of the requested size; like adaptive_pool2d the input
    spatial dims must divide evenly (XLA static windows)."""
    if isinstance(pool_size, int):
        pool_size = [pool_size] * 3
    d, h, w = (int(s) for s in input.shape[2:])
    od, oh, ow = (int(p) for p in pool_size)
    for i_dim, o_dim in ((d, od), (h, oh), (w, ow)):
        if i_dim % o_dim != 0:
            raise ValueError(
                "adaptive_pool3d needs divisible dims, got %d -> %d"
                % (i_dim, o_dim))
    k = [d // od, h // oh, w // ow]
    return nn.pool3d(input, pool_size=k, pool_type=pool_type,
                     pool_stride=k)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT spatial side equals ``out_short_len`` (aspect
    preserved; static shapes from the declared input dims)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    # round-half-up on the long side (reference int(long*s/short + 0.5))
    if h <= w:
        shape = [out_short_len, max(1, int(w * out_short_len / h + 0.5))]
    else:
        shape = [max(1, int(h * out_short_len / w + 0.5)), out_short_len]
    return nn.image_resize(input, out_shape=shape, resample=resample)


def scatter_nd(index, updates, shape, name=None):
    """zeros(shape) scatter-added with updates at index (reference
    scatter_nd_op)."""
    ref = tensor.fill_constant(list(shape), updates.dtype, 0.0)
    return nn.scatter_nd_add(ref, index, updates)


def crop_tensor(x, shape=None, offsets=None, name=None):
    """crop with -1 ("rest of the dim") allowed in shape."""
    offsets = list(offsets or [0] * len(x.shape))
    full = [int(s) for s in x.shape]
    resolved = [full[i] - offsets[i] if s in (-1, None) else int(s)
                for i, s in enumerate(shape)]
    return nn.crop(x, shape=resolved, offsets=offsets)


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix (reference fsp_op): [N, C1, C2]
    = x·yᵀ over spatial positions / (H*W)."""
    n, c1 = x.shape[0], x.shape[1]
    c2 = y.shape[1]
    hw = int(x.shape[2]) * int(x.shape[3])
    fx = nn.reshape(x, [-1, c1, hw])
    fy = nn.transpose(nn.reshape(y, [-1, c2, hw]), [0, 2, 1])
    return nn.scale(nn.matmul(fx, fy), scale=1.0 / hw)


# -- documented-unsupported (dynamic-shape semantics XLA can't express) --
def _unsupported(name, alternative):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            "%s is not supported on the TPU build (%s)" % (name,
                                                           alternative))

    fn.__name__ = name
    fn.__doc__ = "Unsupported on TPU: use %s." % alternative
    return fn


similarity_focus = _unsupported(
    "similarity_focus", "compose topk + one_hot masks for the same effect")
prroi_pool = _unsupported(
    "prroi_pool", "roi_align (bilinear-sampled RoI pooling)")
deformable_conv = _unsupported(
    "deformable_conv", "grid_sampler + conv2d composition")
deformable_roi_pooling = _unsupported(
    "deformable_roi_pooling", "grid_sampler + roi_align composition")
filter_by_instag = _unsupported(
    "filter_by_instag",
    "mask rows host-side in the Dataset/DataLoader pipeline")
reorder_lod_tensor_by_rank = _unsupported(
    "reorder_lod_tensor_by_rank",
    "argsort + gather over the bounded-LoD lengths")


def _select(cond, x, y):
    """Elementwise select (jnp.where semantics, the "where" op's
    3-input form): rows where ``cond`` is true take ``x``, others take
    ``y`` — a true select, so NaN/Inf produced by the branch a row did
    NOT take cannot leak into it (mask-multiply merges would)."""
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="where",
                     inputs={"Condition": [cond], "X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


class IfElse:
    """Row-wise conditional (reference ``control_flow.py:2078``): rows of
    the batch where ``cond`` is true flow through the true block, the
    rest through the false block, and ``ie()`` merges them back in
    order. TPU-native redesign: the reference gathers each subset and
    runs only that block on it (dynamic row counts); under XLA both
    blocks run on the FULL batch and the merge is a row-wise select —
    bit-identical results for the row-independent computations IfElse
    supports, at the cost of evaluating both branches (the standard
    XLA/`lax.select` trade).

        ie = IfElse(cond)                 # cond: [B, 1] bool
        with ie.true_block():
            d = ie.input(x)
            ie.output(true_fn(d))
        with ie.false_block():
            d = ie.input(x)
            ie.output(false_fn(d))
        out, = ie()
    """

    def __init__(self, cond, name=None):
        self._cond = cond
        self._outs = {True: [], False: []}
        self._in_branch = None

    import contextlib

    @contextlib.contextmanager
    def true_block(self):
        self._in_branch = True
        try:
            yield
        finally:
            self._in_branch = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_branch = False
        try:
            yield
        finally:
            self._in_branch = None

    def input(self, x):
        assert self._in_branch is not None, \
            "IfElse.input() only inside true_block()/false_block()"
        # both-branch trace: the block sees the full batch; masking
        # happens at merge time
        return x

    def output(self, *outs):
        assert self._in_branch is not None, \
            "IfElse.output() only inside true_block()/false_block()"
        self._outs[self._in_branch].extend(outs)

    def __call__(self):
        from . import nn, tensor

        t, f = self._outs[True], self._outs[False]
        assert len(t) == len(f) and t, (
            "IfElse: both blocks must emit the same number of outputs")
        return [_select(self._cond, tv, fv) for tv, fv in zip(t, f)]


class DynamicRNN:
    """Variable-length block-style RNN (reference
    ``control_flow.py:2250``). TPU-native redesign over the bounded-LoD
    substrate: instead of the reference's sort-by-length batch
    shrinking, the step body runs for every sequence at every step and
    ``update_memory`` masks state updates past each row's length — the
    same math, static shapes. The step block is traced once into a
    StaticRNN (lax.scan); inputs are bounded-LoD sequences
    (``sequence_pad`` supplies the [B, T, D] view and lengths); outputs
    come back dense [B, T, D] with steps past each row's length zeroed.

        drnn = DynamicRNN(maxlen=T)
        with drnn.block():
            x_t = drnn.step_input(x)          # x: bounded-LoD [total, D]
            h_prev = drnn.memory(shape=[H], value=0.0, batch_ref=x_t)
            h = some_layers(x_t, h_prev)
            drnn.update_memory(h_prev, h)
            drnn.output(h)
        out = drnn()                          # [B, T, H]
    """

    def __init__(self, name=None, maxlen=None):
        from .control_flow import StaticRNN

        self._rnn = StaticRNN(name=name or "dynamic_rnn")
        self._maxlen = maxlen
        self._lengths = None       # [B] int lengths (outer block)
        self._padded_ref = None    # [B, T, D] padded view (outer block)
        self._t = None             # [1] step counter (step block)
        self._mask = None          # [B, 1] in-step validity mask
        self._helper = LayerHelper(name or "dynamic_rnn")

    import contextlib

    @contextlib.contextmanager
    def block(self):
        with self._rnn.step():
            yield

    def _step_mask(self):
        from .control_flow import less_than

        if self._mask is None:
            assert self._t is not None, "call step_input() first"
            self._mask = less_than(self._t, self._lengths)  # [B] bool
        return self._mask

    def _rowwise_mask(self, ref):
        """[B] bool, unsqueezed to [B, 1] for rank>=2 operands so the
        select broadcasts row-wise."""
        from . import nn

        mask = self._step_mask()
        if len(ref.shape) >= 2 or not ref.shape:
            mask = nn.unsqueeze(mask, [1])
        return mask

    def step_input(self, x, level=0):
        """x: bounded-LoD sequence ([total_bound, D] + @LOD lengths).
        Returns the per-step [B, D] slice inside the block."""
        from . import nn, sequence_lod, tensor

        assert self._maxlen is not None, (
            "DynamicRNN(maxlen=T) is required: XLA needs the static step "
            "bound (the bounded-LoD analogue of the reference's dynamic "
            "max length)")
        program = self._helper.main_program
        blk_idx = program.current_block_idx
        # build the padded view + counter in the PARENT block
        program.current_block_idx = self._rnn._block.parent_idx
        pad0 = tensor.fill_constant([1], x.dtype, 0.0)
        padded, length = sequence_lod.sequence_pad(
            x, pad0, maxlen=self._maxlen)               # [B, T, D], [B]
        if self._lengths is None:
            self._lengths = tensor.cast(length, "int32")
            self._padded_ref = padded
        rank = len(x.shape) + 1                         # padded adds T
        tm = nn.transpose(padded, [1, 0] + list(range(2, rank)))
        if self._t is None:
            T = int(self._maxlen)
            counter = nn.reshape(tensor.range(0, T, 1, "int32"), [T, 1])
            self._t = self._rnn.step_input(counter)     # [1] per step
        program.current_block_idx = blk_idx
        return self._rnn.step_input(tm)

    def static_input(self, x):
        """Non-sequence input visible in every step (closure capture —
        the step block reads outer vars directly)."""
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32", batch_ref=None):
        from . import nn, tensor

        if init is None and shape is not None:
            assert self._padded_ref is not None, (
                "call step_input() before memory(shape=...)")
            program = self._helper.main_program
            cur = program.current_block_idx
            program.current_block_idx = self._rnn._block.parent_idx
            # batch size comes from the padded input at lowering time
            init = tensor.fill_constant_batch_size_like(
                self._padded_ref, shape=[1] + list(shape), dtype=dtype,
                value=value, input_dim_idx=0, output_dim_idx=0)
            program.current_block_idx = cur
            return self._rnn.memory(init=init)
        return self._rnn.memory(init=init, shape=shape, value=value,
                                dtype=dtype)

    def update_memory(self, ex_mem, new_mem):
        """Masked update: rows whose sequence already ended keep their
        previous state (the reference achieves this by shrinking the
        batch; masking is the static-shape equivalent)."""
        merged = _select(self._rowwise_mask(new_mem), new_mem, ex_mem)
        self._rnn.update_memory(ex_mem, merged)

    def output(self, *outputs):
        """Per-step outputs, zeroed past each row's length."""
        from . import nn

        for o in outputs:
            zero = tensor.fill_constant([1], o.dtype, 0.0)
            self._rnn.step_output(
                _select(self._rowwise_mask(o), o, zero))

    def __call__(self):
        from . import nn

        outs = self._rnn()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        dense = [nn.transpose(o, [1, 0] +
                              list(range(2, max(len(o.shape), 2))))
                 for o in outs]                          # [B, T, ...]
        return dense[0] if len(dense) == 1 else dense


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution over (features, edges) trees (reference
    ``contrib/layers/nn.py`` tree_conv + ``tree_conv_op.cc``). Returns
    ``[batch, nodes, output_size, num_filters]`` after bias and act."""
    helper = LayerHelper("tree_conv", **locals())
    feature_size = int(nodes_vector.shape[-1])
    w = helper.create_parameter(
        attr=param_attr, shape=[feature_size, 3, output_size, num_filters],
        dtype=nodes_vector.dtype)
    out = helper.create_variable_for_type_inference(nodes_vector.dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": int(max_depth)})
    if bias_attr is not False:  # repo convention: only False disables bias
        bias = helper.create_parameter(attr=bias_attr, shape=[num_filters],
                                       dtype=nodes_vector.dtype,
                                       is_bias=True)
        out = nn.elementwise_add(out, bias, axis=-1)
    return helper.append_activation(out, act)
